"""Host-driven asynchronous parameter server: real `dist_async`.

ref: src/kvstore/kvstore_dist_server.h:346-359 — in async mode the
server applies each worker's push IMMEDIATELY (`ApplyUpdates` without
the NumWorkers aggregation barrier), so workers train on stale weights;
convergence behavior genuinely differs from dist_sync. The ICI
collectives that back dist_sync are inherently synchronous, so — as
SURVEY §5 prescribes — async runs over a host-side transport: a server
thread in the rank-0 process owns the weights and applies updates as
messages arrive over TCP; pulls return whatever mix of updates has
landed. This is the ps-lite worker/server split with the scheduler
folded into the launcher's coordinator env.

Wire protocol (no pickle on the data plane — a remote peer can never
make the server deserialize executable objects from a push/pull):

  frame   := u32_be length | payload
  payload := opcode:u8 | fields
  key     := 0x00 i64_be        (int key)
           | 0x01 u16_be utf8   (str key)
  array   := u8 dtype-name-len | dtype-name | u8 ndim | u32_be dims...
           | raw C-order bytes

The ONE message that must carry a Python object — `set_optimizer`, the
reference's pickled-optimizer-to-server UX (python/mxnet/kvstore_server.py
``_controller``) — is authenticated: payload is HMAC-SHA256(secret,
blob) || blob, and the server refuses to unpickle unless the MAC
verifies. The secret comes from ``MXTPU_PS_SECRET`` (distributed to all
ranks by the launcher env pass-through, tools/launch.py); rank 0
generates one when unset so single-host runs are safe by default.

Wire trace-context (ISSUE 6): a client that negotiated protocol
version >= 1 (the ``_OP_HELLO`` rendezvous at connect; an old server
answers unknown-opcode ``_RE_ERR`` and the client falls back to the
unstamped wire, so mixed fleets interop) sets the high bit of the
opcode byte while a profile run is active and prefixes the payload
with a 20-byte context ``rank:i32 | req_id:u64 | send_ts_us:f64``.
The server strips it, records a ``ps.server.<op>`` span keyed by the
id, and both sides emit chrome-trace flow events (``ph:"s"``
client-side, ``ph:"f"`` server-side) so the merged multi-rank trace
(``tools/trace_merge.py``) draws client→server causality arrows per
push/pull/barrier. Profiling off = opcode byte and payload are
byte-identical to the v0 wire (the zero-overhead contract,
benched by ``BENCH_MODEL=profiler_overhead``).
"""
from __future__ import annotations

import hashlib
import hmac
import itertools
import os
import pickle
import secrets as _secrets
import socket
import struct
import threading
from statistics import median as _median
import time as _ptime
import warnings
import weakref

import numpy as np

from . import _retry
from . import kvstore_server as _kvstore_server
from . import profiler as _profiler
from ._debug import faultpoint as _faultpoint
from ._debug import healthmon as _healthmon
from ._debug import locktrace as _locktrace
from ._debug import watchdog as _watchdog
from .base import getenv as _getenv
from .base import getenv_dynamic as _getenv_dynamic

__all__ = ["AsyncPSServer", "AsyncPSClient", "serve_if_rank0"]

# request opcodes
_OP_INIT = 1
_OP_PUSH = 2
_OP_PULL = 3
_OP_SET_OPT = 4
_OP_STATS = 5
_OP_DONE = 6
_OP_WAIT_DONE = 7
_OP_STOP = 8
# reserved for the sparse/compressed wire (row-sparse push/pull and
# 2-bit compressed push ride the same framing)
_OP_PUSH_RSP = 9
_OP_PULL_RSP = 10
_OP_PUSH_2BIT = 11
_OP_PROFILER = 12
_OP_HEARTBEAT = 13
_OP_DEADNODES = 14
_OP_SHAPE = 15
_OP_BARRIER = 16
_OP_HELLO = 17
# peer-snapshot plane (ISSUE 19c): a rank publishes its newest
# in-memory training state as an opaque blob; a recovering rank pulls
# the freshest live peer's copy before falling back to the checkpoint
# filesystem. Length-gated like every op since PR 8: a v0 server
# answers both with _RE_ERR ("unknown opcode"), which the client
# surfaces as RuntimeError and elastic counts as a filesystem fallback
# — old-server interop is the degraded path, never a hang or a crash.
_OP_SNAP_PUT = 18
_OP_SNAP_GET = 19
# control-plane survivability (ISSUE 20): fencing epochs + coordinated
# preemption. _OP_EPOCH proposes/queries the server's monotonic fencing
# epoch (>q proposed; -1 or any lower value queries, a higher value is
# adopted and journaled; reply _RE_INT is the committed epoch).
# _OP_PREEMPT announces a rank is draining after SIGTERM (>qq
# rank|step): dead-node queries include it immediately so peers reshard
# proactively instead of burning the heartbeat timeout. Length-gated
# like every op since PR 8 — a v0 server answers unknown-opcode _RE_ERR
# and callers count-and-continue.
_OP_EPOCH = 20
_OP_PREEMPT = 21

# response opcodes
_RE_OK = 0x10
_RE_ARR = 0x11
_RE_INT = 0x12
_RE_BYTES = 0x13
_RE_ERR = 0x1F

# protocol version this build speaks; negotiated per connection by the
# _OP_HELLO rendezvous. v1 adds the wire trace-context (opcode high bit
# + 20-byte header), the timestamped heartbeat (clock sync), and the
# _OP_PROFILER 'metrics' pull. v0 peers simply never see any of it.
_PROTO_VERSION = 1
# opcode high bit: "a trace-context header follows the opcode byte"
_TRACE_FLAG = 0x80
_CTX_FMT = ">iQd"   # rank:i32 | req_id:u64 | client send-ts (trace us)
_CTX_SIZE = struct.calcsize(_CTX_FMT)

_OP_NAMES = {
    _OP_INIT: "init", _OP_PUSH: "push", _OP_PULL: "pull",
    _OP_SET_OPT: "set_optimizer", _OP_STATS: "stats", _OP_DONE: "done",
    _OP_WAIT_DONE: "wait_done", _OP_STOP: "stop",
    _OP_PUSH_RSP: "push_rsp", _OP_PULL_RSP: "pull_rsp",
    _OP_PUSH_2BIT: "push_2bit", _OP_PROFILER: "profiler",
    _OP_HEARTBEAT: "heartbeat", _OP_DEADNODES: "dead_nodes",
    _OP_SHAPE: "shape", _OP_BARRIER: "barrier", _OP_HELLO: "hello",
    _OP_SNAP_PUT: "snapshot_put", _OP_SNAP_GET: "snapshot_get",
    _OP_EPOCH: "fence_epoch", _OP_PREEMPT: "preempt_notice",
}

# Journal-only record tags (never on the wire — the high range cannot
# collide with request opcodes): _J_STORE is a store-replace synthetic
# record compaction writes into table.snap, _J_EPOCH persists a fencing
# epoch bump, _J_HEALTH persists a rank's newest SDC digest so a
# restarted server still holds the divergence evidence.
_J_HEALTH = 0xF0
_J_EPOCH = 0xF1
_J_STORE = 0xF2


def _fencing_enabled():
    """MXTPU_PS_FENCING switch (ISSUE 20a): when on, clients stamp every
    push with their fencing epoch and the server rejects writes stamped
    below its committed epoch — a rank partitioned across an elastic
    reshard can never write stale state back into aggregation."""
    return _getenv("MXTPU_PS_FENCING", "0") not in ("0", "", "false",
                                                    "off")


# Ops whose handler blocks waiting on OTHER workers (cross-worker
# rendezvous): their duration measures straggler skew, not server apply
# cost, so they stay out of the kvstore.server_handle histogram.
_RENDEZVOUS_OPS = frozenset((_OP_BARRIER, _OP_WAIT_DONE))

# One process-wide request-id sequence shared by every AsyncPSClient in
# the rank (per-server shard clients, the fresh tmp client each barrier()
# creates, ...): per-client counters would all start at 0 and collide in
# _flow_id, cross-wiring client->server causality arrows in the merged
# trace. next() on itertools.count is atomic under the GIL.
_REQ_SEQ = itertools.count(1)


def _flow_id(rank, req_id):
    """Job-unique chrome-trace flow id for one request: the stamping
    rank in the top bits so concurrent ranks never collide."""
    return ((rank & 0xFFFF) << 48) | (req_id & 0xFFFFFFFFFFFF)


def _ps_secret():
    s = _getenv("MXTPU_PS_SECRET", "")
    return s.encode() if s else None


def _pack_key(key):
    if isinstance(key, (int, np.integer)):
        return b"\x00" + struct.pack(">q", int(key))
    kb = str(key).encode()
    return b"\x01" + struct.pack(">H", len(kb)) + kb


def _unpack_key(buf, off):
    tag = buf[off]
    off += 1
    if tag == 0:
        return struct.unpack_from(">q", buf, off)[0], off + 8
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n


def _pack_arr(a):
    a = np.ascontiguousarray(a)
    dt = a.dtype.name.encode()
    out = [struct.pack(">B", len(dt)), dt, struct.pack(">B", a.ndim)]
    out.append(struct.pack(">%dI" % a.ndim, *a.shape))
    out.append(a.tobytes())
    return b"".join(out)


def _unpack_arr(buf, off):
    n = buf[off]
    off += 1
    dt = np.dtype(buf[off:off + n].decode())
    off += n
    ndim = buf[off]
    off += 1
    shape = struct.unpack_from(">%dI" % ndim, buf, off)
    off += 4 * ndim
    count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
    nbytes = count * dt.itemsize
    arr = np.frombuffer(buf, dtype=dt, count=count, offset=off
                        ).reshape(shape).copy()
    return arr, off + nbytes


def _net_chaos_send():
    """On-the-wire chaos, send side (ISSUE 20c). Called only when
    faultpoints are ACTIVE. Returns True when the frame should be
    silently swallowed (``net.drop``: sent locally, never arrives — the
    caller then blocks in recv until ``MXTPU_PS_RECV_TIMEOUT`` surfaces
    it as a counted retry). ``net.partition`` raises its configured
    exception out of the send seam exactly where a dead link would;
    ``net.delay`` sleeps in-line (a slow/congested link)."""
    try:
        if _faultpoint.check("net.drop"):
            return True
    except Exception:
        # any configured action on net.drop means "drop the frame" —
        # a raise here would model a *visible* failure, which is what
        # net.partition is for. Counted: a dropped frame is degradation.
        _profiler.account("kvstore.net_chaos_drops", 1, emit=False)
        return True
    _faultpoint.check("net.partition")
    _faultpoint.check("net.delay")
    return False


def _net_chaos_recv(sock):
    """On-the-wire chaos, recv side (ISSUE 20c). ``net.partition`` /
    ``net.delay`` behave as on the send seam. ``net.half_open`` models a
    peer that holds the connection open but goes silent: the point's
    configured delay is the silent period; when the socket carries a
    recv timeout (``MXTPU_PS_RECV_TIMEOUT``) the seam then raises the
    same ``socket.timeout`` a real silent peer would produce, otherwise
    the stall simply passes (slow-but-alive peer)."""
    _faultpoint.check("net.partition")
    _faultpoint.check("net.delay")
    if _faultpoint.check("net.half_open") \
            and sock.gettimeout() is not None:
        raise socket.timeout(
            "faultpoint 'net.half_open': peer went silent past the "
            "recv timeout")


def _send_frame(sock, payload):
    if _faultpoint.ACTIVE and _net_chaos_send():
        return
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock):
    if _faultpoint.ACTIVE:
        _net_chaos_recv(sock)
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    n = struct.unpack(">I", hdr)[0]
    return _recv_exact(sock, n)


# live servers hosted in this process, for the kvstore_server stats
# provider below (weak: a stopped/collected server drops out on its own)
_SERVERS = weakref.WeakSet()


def _server_stats():
    """``metrics()['kvstore_server']``: per-rank heartbeat staleness as
    the ``rank_heartbeat_age.<rank>`` gauge (seconds since that rank's
    last beat — operators see a rank going stale BEFORE the
    barrier-timeout autopsy names it dead) plus apply/done totals,
    aggregated over every live server hosted in this process.

    Straggler detection (ISSUE 8): each rank's v1 heartbeat carries the
    duration of its newest completed training step (the watchdog
    beacon), so the server sees every rank's step time without an extra
    round trip — durations are interval measurements on each rank's own
    monotonic clock, so no cross-rank clock alignment is needed (the
    beat *timestamps* ride the PR 6 clock-sync exchange). With >= 2
    reporting ranks the gauges name who is slow:

    - ``rank_step_s.<r>``: newest completed step duration of rank r
    - ``step_skew.<r>``: that duration over the median of the OTHER
      ranks' durations (leave-one-out — with few ranks a straggler
      would otherwise drag the baseline up toward itself and mask its
      own skew)
    - ``straggler.<r>`` = 1 and ``stragglers`` list membership when the
      skew exceeds ``MXTPU_STRAGGLER_FACTOR`` (default 2.0)

    SDC divergence (ISSUE 15): each rank's v1 heartbeat also carries
    its newest grad-bucket digest ``(health seq, CRC32)`` from
    ``_debug/healthmon``. Same-seq digests must be bitwise-identical
    under DP replication, so at the step the most ranks report:

    - ``rank_health_seq.<r>``: newest digest seq of rank r
    - ``sdc_divergence`` = 1 when same-seq checksums disagree
    - ``sdc_suspect.<r>`` = 1 / ``sdc_suspects`` membership: the ranks
      off the majority checksum (with only two ranks there is no
      majority — divergence is certain, attribution is not, both are
      flagged)
    """
    out = {}
    now = _ptime.monotonic()
    try:
        factor = float(_getenv("MXTPU_STRAGGLER_FACTOR", "2.0")
                       or 2.0)
    except ValueError:
        factor = 2.0
    try:
        stale_s = float(_getenv("MXTPU_PS_DEAD_TIMEOUT", "3.0")
                        or 3.0)
    except ValueError:
        stale_s = 3.0
    durs = {}
    health = {}
    for srv in list(_SERVERS):
        with srv._lock:
            beats = dict(srv._heartbeats)
            steps = dict(srv._step_stats)
            hstats = dict(srv._health_stats)
            out["updates_applied"] = out.get("updates_applied", 0) \
                + srv.updates_applied
            out["workers_done"] = out.get("workers_done", 0) \
                + srv.workers_done
        for rank, (hseq, hsum, at) in hstats.items():
            if now - at > stale_s:
                continue  # a dead rank's digest must not sit forever
            cur = health.get(rank)
            if cur is None or hseq > cur[0]:
                health[rank] = (hseq, hsum)
        for rank, t in beats.items():
            key = "rank_heartbeat_age.%d" % rank
            out[key] = max(out.get(key, 0.0), round(now - t, 3))
        for rank, (dur, seq, at) in steps.items():
            if now - at > stale_s:
                # the rank stopped beating (every beat refreshes its
                # entry): a dead rank's last duration must not sit in
                # the skew baseline — or the straggler list — forever
                continue
            durs[rank] = max(durs.get(rank, 0.0), dur)
            out["rank_step_s.%d" % rank] = round(durs[rank], 6)
            out["rank_step_seq.%d" % rank] = seq
    if len(durs) >= 2:
        stragglers = []
        for rank, dur in durs.items():
            others = _median([d for r, d in durs.items() if r != rank])
            if others <= 0:
                continue
            skew = dur / others
            out["step_skew.%d" % rank] = round(skew, 3)
            if skew > factor:
                out["straggler.%d" % rank] = 1
                stragglers.append(rank)
        out["stragglers"] = sorted(stragglers)
        out["straggler_count"] = len(stragglers)
    for rank, (hseq, _hsum) in sorted(health.items()):
        out["rank_health_seq.%d" % rank] = hseq
    if len(health) >= 2:
        # SDC divergence (ISSUE 15): compare checksums at the step the
        # most ranks report. Under DP replication the reduced update is
        # bitwise-shared, so same-seq digests must be identical — a
        # divergent rank is computing different numbers from the same
        # inputs (silent data corruption), exactly the leave-one-out
        # shape of the straggler skew above.
        seq_groups = {}
        for rank, (hseq, hsum) in health.items():
            seq_groups.setdefault(hseq, {})[rank] = hsum
        cmp_seq, members = max(seq_groups.items(),
                               key=lambda kv: (len(kv[1]), kv[0]))
        suspects = []
        if len(members) >= 2:
            counts = {}
            for s in members.values():
                counts[s] = counts.get(s, 0) + 1
            top_n = max(counts.values())
            divergent = len(counts) > 1
            out["sdc_divergence"] = int(divergent)
            out["sdc_checked_seq"] = cmp_seq
            if divergent:
                if top_n * 2 > len(members):
                    # a strict majority pins the truth: whoever is off
                    # it is the suspect (>= 3 ranks names the bad one)
                    top_sum = max(counts, key=lambda s: counts[s])
                    suspects = sorted(r for r, s in members.items()
                                      if s != top_sum)
                else:
                    # no majority (two ranks disagreeing): divergence
                    # is certain, attribution is not — flag all
                    suspects = sorted(members)
            for r in suspects:
                out["sdc_suspect.%d" % r] = 1
            out["sdc_suspects"] = suspects
            out["sdc_suspect_count"] = len(suspects)
    return out


_profiler.register_stats_provider("kvstore_server", _server_stats)


class AsyncPSServer:
    """Weight owner + immediate-apply update loop (the reference's
    KVStoreDistServer in async mode).

    Binds to ``bind_host`` only (loopback by default) — never to
    0.0.0.0 unless the launcher explicitly passes the coordinator
    interface, so the update endpoint is not exposed beyond the
    training fabric."""

    def __init__(self, port=0, bind_host="127.0.0.1", journal_dir=None):
        self._store = {}
        self._updater = None
        self._lock = _locktrace.named_lock("kvstore_async.server")
        self._heartbeats = {}  # rank -> monotonic time of last beat
        # rank -> (step duration s, step seq, monotonic arrival): the
        # per-rank step gauges the v1 heartbeat carries (straggler
        # detection, ISSUE 8)
        self._step_stats = {}
        # rank -> (health seq, grad-digest checksum, monotonic
        # arrival): the SDC divergence payload (ISSUE 15) — under DP
        # replication same-seq checksums must agree bitwise
        self._health_stats = {}
        self._barrier_cv = _locktrace.named_condition(
            "kvstore_async.server", self._lock)
        self._barrier_count = 0
        self._barrier_gen = 0
        # peer-snapshot table (ISSUE 19c): opaque per-rank state blobs
        # served back to recovering ranks; liveness-filtered against
        # self._heartbeats at get time
        self._snapshots = _kvstore_server.SnapshotTable()
        if _ps_secret() is None:
            # same-host workers inherit this via the environment; the
            # launcher passes MXTPU_* through for remote ranks
            os.environ["MXTPU_PS_SECRET"] = _secrets.token_hex(32)
        # pinned at construction: later env mutation must not change
        # what the server trusts
        self._secret = _ps_secret()
        # control-plane survivability (ISSUE 20a): monotonic fencing
        # epoch (bumped by _OP_EPOCH on every elastic reshard; writes
        # stamped below it are rejected), preemption notices (rank ->
        # (step, arrival) — merged into dead-node replies so peers
        # reshard proactively), and the optional mutation journal. The
        # journal REPLAYS before the socket binds: a restarted server
        # is back at its pre-death state before the first client can
        # reach it.
        self._epoch = 0
        self._preempted = {}
        self._journal = None
        self._journal_lock = _locktrace.named_lock(
            "kvstore_async.journal")
        self.journal_replayed = 0
        self.updates_applied = 0          # observability for tests
        self.workers_done = 0
        self._journal_dir = (journal_dir if journal_dir is not None
                             else _getenv("MXTPU_PS_JOURNAL_DIR", ""))
        if self._journal_dir:
            self._journal_open()
        self.bind_host = bind_host
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        _SERVERS.add(self)  # feeds the kvstore_server stats provider

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                buf = _recv_frame(conn)
            except OSError:
                return
            if buf is None or not len(buf):
                return
            ctx = None
            if buf[0] & _TRACE_FLAG and len(buf) > _CTX_SIZE:
                # v1 wire trace-context: strip (rank, req_id, send_ts)
                # so _handle sees the plain v0 payload
                ctx = struct.unpack_from(_CTX_FMT, buf, 1)
                buf = bytes([buf[0] & ~_TRACE_FLAG]) + buf[1 + _CTX_SIZE:]
            t0 = _ptime.perf_counter() if _profiler._LIVE else None
            try:
                self._handle(conn, buf)
            except Exception as e:  # noqa: BLE001 — reply, don't die
                _profiler.account("kvstore.server_errors", 1,
                                  emit=False)
                msg = ("%s: %s" % (type(e).__name__, e)).encode()[:4096]
                try:
                    _send_frame(conn, struct.pack(">BH", _RE_ERR, len(msg))
                                + msg)
                except OSError:
                    return
            if t0 is not None:
                # server-side span per request; when the request carried
                # trace-context, key it by (rank, req_id) and close the
                # flow the client opened — the merged trace then shows
                # client→server causality per push/pull/barrier
                dur = (_ptime.perf_counter() - t0) * 1e6
                opname = _OP_NAMES.get(buf[0], "op%d" % buf[0])
                args = None
                if ctx is not None:
                    args = {"rank": ctx[0], "req_id": ctx[1],
                            "client_send_ts_us": ctx[2]}
                _profiler.record_op("ps.server.%s" % opname, dur,
                                    category="kvstore", lane="kvstore",
                                    args=args)
                if ctx is not None:
                    _profiler.record_flow(
                        "ps.%s" % opname, _flow_id(ctx[0], ctx[1]), "f",
                        ts_us=_profiler._now_us() - dur)
                if buf[0] not in _RENDEZVOUS_OPS:
                    # barrier/wait_done block for cross-worker
                    # rendezvous (seconds, straggler-bound) — folding
                    # those waits in would swamp the apply-cost tail
                    # this histogram isolates
                    _profiler.record_latency("kvstore.server_handle",
                                             dur)
            if buf[0] == _OP_STOP:
                return

    def _handle(self, conn, buf):
        op, off = buf[0], 1
        if op == _OP_INIT:
            key, off = _unpack_key(buf, off)
            arr, off = _unpack_arr(buf, off)
            with self._lock:
                self._store.setdefault(key, arr)
                self._journal_append(buf, maybe_compact=True)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_PUSH:
            key, off = _unpack_key(buf, off)
            grad, off = _unpack_arr(buf, off)
            self._check_fence(buf, off)
            # IMMEDIATE apply — no cross-worker barrier (async
            # semantics, kvstore_dist_server.h:358)
            with self._lock:
                if self._updater is not None:
                    self._apply(key, grad)
                else:
                    # same store-replace semantics as the sync
                    # KVStore without an optimizer (kvstore.py push)
                    self._store[key] = grad.copy()
                self.updates_applied += 1
                self._journal_append(buf, maybe_compact=True)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_PULL:
            key, off = _unpack_key(buf, off)
            with self._lock:
                val = np.array(self._store[key], copy=True)
            _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(val))
        elif op == _OP_SET_OPT:
            # the reference pickles the optimizer worker-side and the
            # server builds its updater from it (kvstore_server.py).
            # The blob is executable on unpickle, so it MUST carry a
            # valid HMAC — an unauthenticated peer cannot reach
            # pickle.loads.
            mac, blob = buf[off:off + 32], buf[off + 32:]
            if self._secret is None:
                raise RuntimeError(
                    "server has no MXTPU_PS_SECRET; refusing pickled "
                    "optimizer (launcher must distribute the secret)")
            want = hmac.new(self._secret, blob, hashlib.sha256).digest()
            if not hmac.compare_digest(mac, want):
                raise PermissionError("set_optimizer HMAC mismatch")
            import mxnet_tpu.optimizer as opt
            optimizer = pickle.loads(blob)
            self._optimizer = optimizer
            self._updater = opt.get_updater(optimizer)
            self._journal_append(buf)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_STATS:
            with self._lock:
                n = self.updates_applied
            _send_frame(conn, struct.pack(">Bq", _RE_INT, n))
        elif op == _OP_DONE:
            # done may carry the finishing rank: a clean finalize
            # DEREGISTERS the node (ps-lite Finalize), so it never shows
            # up as dead — only crashed workers go stale
            with self._lock:
                self.workers_done += 1
                if len(buf) >= off + 8:
                    (rank,) = struct.unpack_from(">q", buf, off)
                    self._heartbeats.pop(int(rank), None)
                    self._step_stats.pop(int(rank), None)
                    # a clean finish WITHDRAWS the preemption notice
                    # too: the rank drained inside its grace budget,
                    # so it must not linger in dead-node replies
                    self._preempted.pop(int(rank), None)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_WAIT_DONE:
            n, timeout = struct.unpack_from(">qd", buf, off)
            import time as _t
            deadline = _t.monotonic() + timeout
            reached = 0
            while True:  # condition first: timeout=0 is a valid poll
                with self._lock:
                    if self.workers_done >= n:
                        reached = 1
                        break
                if _t.monotonic() >= deadline:
                    break
                _t.sleep(0.02)
            _send_frame(conn, struct.pack(">Bq", _RE_INT, reached))
        elif op == _OP_PUSH_RSP:
            # row-sparse push: only touched rows cross the wire
            # (ref: kvstore_dist.h:522 EncodeRowSparseKey)
            key, off = _unpack_key(buf, off)
            rows_idx, off = _unpack_arr(buf, off)
            rows_val, off = _unpack_arr(buf, off)
            self._check_fence(buf, off)
            with self._lock:
                dense = self._store[key]
                ids = rows_idx.astype(np.int64)
                if self._updater is not None:
                    # reference row-sparse semantics: the update runs on
                    # the TOUCHED ROWS only — wd/momentum must not leak
                    # onto untouched rows (kvstore_dist_server.h sparse
                    # DataHandleEx)
                    self._apply_rows(key, ids, rows_val)
                else:
                    dense[ids] = rows_val
                self.updates_applied += 1
                self._journal_append(buf, maybe_compact=True)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_PULL_RSP:
            # pull only the requested rows (row_sparse_pull semantics)
            key, off = _unpack_key(buf, off)
            rows_idx, off = _unpack_arr(buf, off)
            with self._lock:
                rows = np.array(
                    self._store[key][rows_idx.astype(np.int64)],
                    copy=True)
            _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(rows))
        elif op == _OP_PUSH_2BIT:
            # 2-bit quantized push: int32 words + (n, threshold) header;
            # the server dequantizes and applies (ref:
            # gradient_compression.h:38 — async now matches the sync
            # path's wire optimization)
            key, off = _unpack_key(buf, off)
            n, thr = struct.unpack_from(">qd", buf, off)
            off += 16
            words, off = _unpack_arr(buf, off)
            self._check_fence(buf, off)
            from .pallas_kernels.compression import dequantize_2bit_jnp
            import jax.numpy as jnp
            from . import storage as _storage_mod
            packed = jnp.asarray(words)
            # allocation-ledger choke point: transient dequantize
            # scratch on the server is 'workspace' memory
            _storage_mod.ledger_register(packed, "workspace",
                                         site="kvstore.dequantize")
            grad = np.asarray(dequantize_2bit_jnp(
                packed, int(n), float(thr)))
            with self._lock:
                grad = grad.reshape(self._store[key].shape)
                if self._updater is not None:
                    self._apply(key, grad)
                else:
                    self._store[key] = grad.copy()
                self.updates_applied += 1
                self._journal_append(buf, maybe_compact=True)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_SHAPE:
            key, off = _unpack_key(buf, off)
            with self._lock:
                shp = np.asarray(self._store[key].shape, np.int64)
            _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(shp))
        elif op == _OP_BARRIER:
            # rendezvous of n workers (ref: ps::Postoffice::Barrier,
            # kvstore_dist.h:106) — each conn thread blocks until the
            # generation releases. An aborted wait (server stop or
            # timeout) WITHDRAWS its arrival and errors, so a crashed
            # participant cannot poison the next generation and a
            # client never sees a rendezvous that did not happen.
            (n,) = struct.unpack_from(">q", buf, off)
            import time as _t
            timeout = float(_getenv("MXTPU_PS_BARRIER_TIMEOUT",
                                           "600"))
            deadline = _t.monotonic() + timeout
            with self._barrier_cv:
                if self._barrier_count == 0:
                    self._barrier_n = int(n)
                elif int(n) != self._barrier_n:
                    raise ValueError(
                        "barrier size mismatch: %d vs in-progress %d"
                        % (n, self._barrier_n))
                self._barrier_count += 1
                gen = self._barrier_gen
                if self._barrier_count >= self._barrier_n:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                    released = True
                else:
                    while self._barrier_gen == gen \
                            and not self._stop.is_set() \
                            and _t.monotonic() < deadline:
                        self._barrier_cv.wait(0.2)
                    released = self._barrier_gen != gen
                    if not released:
                        self._barrier_count -= 1  # withdraw arrival
                        # name the missing: the heartbeat table (same
                        # lock as the cv) knows who stopped beating, so
                        # the abort tells operators WHO is dead, not
                        # just how many arrivals were short
                        stale = float(_getenv(
                            "MXTPU_PS_DEAD_TIMEOUT", "3.0"))
                        now = _t.monotonic()
                        dead = sorted(
                            r for r, t in self._heartbeats.items()
                            if now - t > stale)
            if not released:
                raise RuntimeError(
                    "barrier aborted (server stopping or %.0fs timeout "
                    "waiting for %d workers); dead ranks (heartbeat "
                    "stale > %.0fs): %s" % (
                        timeout, n, stale,
                        dead if dead else "none known"))
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_HEARTBEAT:
            (rank,) = struct.unpack_from(">q", buf, off)
            import time as _t
            with self._lock:
                self._heartbeats[int(rank)] = _t.monotonic()
                if len(buf) >= off + 32:
                    # trailing (step duration f64, step seq i64): the
                    # rank's newest completed training step — the
                    # straggler gauge payload. Old servers never reach
                    # here (length-gated); old clients never send it.
                    dur, seq = struct.unpack_from(">dq", buf, off + 16)
                    if seq >= 0:
                        # seq=-1 is the no-step-stats placeholder a
                        # watchdog-off client packs so its SDC digest
                        # can still ride the fixed offsets — it must
                        # not enter the straggler gauges as a 0.0 step
                        self._step_stats[int(rank)] = (
                            float(dur), int(seq), _t.monotonic())
                if len(buf) >= off + 48:
                    # trailing (health seq i64, checksum i64): the
                    # rank's newest grad-bucket digest — the SDC
                    # divergence payload (same length-gating contract)
                    hseq, hsum = struct.unpack_from(">qq", buf,
                                                    off + 32)
                    prev = self._health_stats.get(int(rank))
                    self._health_stats[int(rank)] = (
                        int(hseq), int(hsum), _t.monotonic())
                    if prev is None or int(hseq) > prev[0]:
                        # SDC digests are evidence, not liveness:
                        # journal each NEW digest so a restarted
                        # server still holds what each rank last
                        # reported (zero lost digests across failover)
                        self._journal_append(struct.pack(
                            ">Bqqq", _J_HEALTH, int(rank), int(hseq),
                            int(hsum)))
            if len(buf) >= off + 16:
                # v1 beat carries the client's trace-clock timestamp:
                # answer with OUR trace clock so the client can estimate
                # the offset tools/trace_merge.py aligns shards with
                # (the NTP-style exchange of ISSUE 6 tentpole b)
                _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(
                    np.asarray([_profiler._now_us()], np.float64)))
            else:
                _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_HELLO:
            # protocol-version rendezvous: a v1 client asks before ever
            # stamping trace-context. (An OLD server lands in the
            # unknown-opcode ValueError below instead and replies
            # _RE_ERR, which the client reads as version 0 — that
            # asymmetry IS the interop contract.)
            _send_frame(conn, struct.pack(">Bq", _RE_INT,
                                          _PROTO_VERSION))
        elif op == _OP_DEADNODES:
            # ranks whose heartbeat is older than `timeout` seconds
            # (ref: ps-lite GetDeadNodes, kvstore_dist.h:121)
            (timeout,) = struct.unpack_from(">d", buf, off)
            import time as _t
            now = _t.monotonic()
            with self._lock:
                # preempt-announced ranks (ISSUE 20b) are merged in
                # IMMEDIATELY: the notice is the proactive signal that
                # lets peers reshard without burning the heartbeat
                # timeout the stale-beat path below still provides
                dead = sorted(
                    set(r for r, t in self._heartbeats.items()
                        if now - t > timeout) | set(self._preempted))
            arr = np.asarray(dead, np.int64)
            _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(arr))
        elif op == _OP_PROFILER:
            # profiler command channel (ref: KVStoreServerProfilerCommand
            # include/mxnet/kvstore.h:49; exercised by the reference's
            # tests/nightly/test_server_profiling.py)
            (n,) = struct.unpack_from(">H", buf, off)
            off += 2
            cmd = buf[off:off + n].decode()
            off += n
            (m,) = struct.unpack_from(">H", buf, off)
            off += 2
            body = buf[off:off + m].decode()
            reply = self._profiler_command(cmd, body)
            if reply is None:
                _send_frame(conn, bytes([_RE_OK]))
            else:
                _send_frame(conn, struct.pack(">BI", _RE_BYTES,
                                              len(reply)) + reply)
        elif op == _OP_SNAP_PUT:
            # peer snapshot publish (ISSUE 19c): >qq rank|step header,
            # remainder is the opaque HMAC+pickle blob elastic built.
            # The server stores bytes and never unpickles them — the
            # data-plane no-pickle contract holds on this op too.
            rank, step = struct.unpack_from(">qq", buf, off)
            self._snapshots.put(int(rank), int(step), buf[off + 16:])
            self._journal_append(buf)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_SNAP_GET:
            # >qd exclude_rank|stale_timeout: newest snapshot from a
            # live peer other than the requester. _RE_BYTES reply is
            # >qq rank|step then the blob; _RE_INT 0 means no live
            # peer has one (the client returns None and elastic walks
            # to the filesystem).
            exclude, stale = struct.unpack_from(">qd", buf, off)
            with self._lock:
                beats = dict(self._heartbeats)
            best = self._snapshots.get_newest(int(exclude), beats,
                                              float(stale))
            if best is None:
                _send_frame(conn, struct.pack(">Bq", _RE_INT, 0))
            else:
                prank, pstep, blob = best
                body = struct.pack(">qq", prank, pstep) + blob
                _send_frame(conn, struct.pack(">BI", _RE_BYTES,
                                              len(body)) + body)
        elif op == _OP_EPOCH:
            # fencing-epoch rendezvous (ISSUE 20a): >q proposed. A
            # proposal ABOVE the committed epoch adopts it (and
            # journals the bump, so a restarted server keeps fencing
            # the pre-death partition); -1 or any lower value merely
            # queries. Reply is the committed epoch either way.
            (prop,) = struct.unpack_from(">q", buf, off)
            with self._lock:
                if int(prop) > self._epoch:
                    self._epoch = int(prop)
                    self._journal_append(struct.pack(
                        ">Bq", _J_EPOCH, self._epoch))
                cur = self._epoch
            _send_frame(conn, struct.pack(">Bq", _RE_INT, cur))
        elif op == _OP_PREEMPT:
            # coordinated-preemption notice (ISSUE 20b): >qq rank|step.
            # The rank announces it is draining after SIGTERM; the
            # _OP_DEADNODES reply includes it from now on so peers
            # reshard proactively. A clean done() withdraws the notice
            # along with the heartbeat slot.
            rank, step = struct.unpack_from(">qq", buf, off)
            import time as _t
            with self._lock:
                self._preempted[int(rank)] = (int(step), _t.monotonic())
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_STOP:
            _send_frame(conn, bytes([_RE_OK]))
            self._stop.set()
        else:
            raise ValueError("unknown opcode %d" % op)

    def _check_fence(self, buf, off):
        """Length-gated fencing check (ISSUE 20a): a fencing client
        appends ``>q epoch`` after a push op's v0 fields; absent tail
        (v0/unfenced wire) or a negative stamp means unfenced — interop
        untouched. A stamp BELOW the committed epoch is the signature
        of a rank partitioned across an elastic reshard: reject before
        apply, counted, so split-brain can never corrupt aggregation."""
        if len(buf) < off + 8:
            return
        (ep,) = struct.unpack_from(">q", buf, off)
        if ep < 0:
            return
        with self._lock:
            cur = self._epoch
        if ep < cur:
            _profiler.account("kvstore.fenced_writes", 1, emit=False)
            raise RuntimeError(
                "fenced epoch %d < server epoch %d: stale write from a "
                "rank partitioned across an elastic reshard rejected"
                % (ep, cur))

    @staticmethod
    def _profiler_command(cmd, body):
        """Run a profiler command on the SERVER process (the reference
        forwards SetConfig/State/Pause/Dump enums to each server).
        ``metrics`` returns the server's own ``profiler.metrics()``
        snapshot as JSON bytes — any worker can pull the PS server's
        telemetry (latency histograms included) into the merged view."""
        import json as _json
        from . import profiler
        if cmd == "set_config":
            kwargs = {}
            for part in body.split(","):
                if "=" in part:
                    k, v = part.split("=", 1)
                    kwargs[k.strip()] = (v.strip() == "True"
                                         if v.strip() in ("True", "False")
                                         else v.strip())
            profiler.set_config(**kwargs)
        elif cmd == "state":
            profiler.set_state(body or "run")
        elif cmd == "dump":
            profiler.dump()
        elif cmd == "metrics":
            return _json.dumps(profiler.metrics()).encode()
        else:
            raise ValueError("unknown profiler command %r" % cmd)
        return None

    def _apply_rows(self, key, ids, grad_rows):
        import mxnet_tpu as mx
        from .kvstore import _str_key_int
        w = mx.nd.array(self._store[key][ids])
        g = mx.nd.array(grad_rows)
        self._updater(key if isinstance(key, int) else _str_key_int(key),
                      g, w)
        self._store[key][ids] = w.asnumpy()

    def _apply(self, key, grad):
        import mxnet_tpu as mx
        w = mx.nd.array(self._store[key])
        g = mx.nd.array(grad)
        from .kvstore import _str_key_int
        self._updater(key if isinstance(key, int) else _str_key_int(key),
                      g, w)
        self._store[key] = w.asnumpy()

    # -- mutation journal (ISSUE 20a) ------------------------------------
    # Records are the v0 wire payloads themselves, framed exactly like
    # the wire (u32_be length | payload) and appended to seg_NNNNNN.jnl
    # files opened unbuffered, so every applied mutation hits the OS
    # before the reply goes out and an abrupt server death loses at most
    # the one in-flight record (the replay tolerates a torn tail).
    # Compaction rewrites the whole table as synthetic _J_STORE records
    # into table.tmp and atomically renames it to table.snap (the
    # CheckpointManager temp+rename publish idiom), then drops the
    # replayed segments. With a server-side optimizer installed the
    # updater's state cannot be re-derived from raw store values, so the
    # event-sourced segments ARE the state: compaction only rotates.

    _JOURNAL_SEG_BYTES = 4 << 20

    def _journal_open(self):
        """Replay table.snap + every segment in order into the tables,
        then open a fresh append segment. Runs in __init__ BEFORE the
        socket binds."""
        os.makedirs(self._journal_dir, exist_ok=True)
        snap = os.path.join(self._journal_dir, "table.snap")
        if os.path.exists(snap):
            self.journal_replayed += self._journal_replay_file(snap)
        self._segments = sorted(
            n for n in os.listdir(self._journal_dir)
            if n.startswith("seg_") and n.endswith(".jnl"))
        for n in self._segments:
            self.journal_replayed += self._journal_replay_file(
                os.path.join(self._journal_dir, n))
        self._jseq = max([int(n[4:-4]) for n in self._segments]
                         or [0]) + 1
        self._segment_path = os.path.join(
            self._journal_dir, "seg_%06d.jnl" % self._jseq)
        self._journal = open(self._segment_path, "ab", buffering=0)
        self._journal_bytes = 0

    def _journal_replay_file(self, path):
        """Apply every complete record in one journal file; a torn
        final record (the mutation in flight when the server died) ends
        the replay cleanly, and a record that fails to apply is counted
        (kvstore.journal_skipped) instead of poisoning the rest."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return 0
        count, off = 0, 0
        while off + 4 <= len(data):
            (n,) = struct.unpack_from(">I", data, off)
            if off + 4 + n > len(data):
                break  # torn tail
            try:
                self._replay_record(data[off + 4:off + 4 + n])
                count += 1
            except Exception:  # noqa: BLE001 — skip-and-count
                _profiler.account("kvstore.journal_skipped", 1,
                                  emit=False)
            off += 4 + n
        return count

    def _replay_record(self, buf):
        """One journaled mutation, mirroring _handle's apply semantics
        without a connection. Trailing bytes past the known fields (the
        fencing-epoch tail a v1.1 client stamps) are ignored exactly as
        the length-gated wire ignores them."""
        op, off = buf[0], 1
        if op == _J_STORE:
            key, off = _unpack_key(buf, off)
            arr, _ = _unpack_arr(buf, off)
            self._store[key] = arr
        elif op == _J_EPOCH:
            (ep,) = struct.unpack_from(">q", buf, off)
            self._epoch = max(self._epoch, int(ep))
        elif op == _J_HEALTH:
            rank, hseq, hsum = struct.unpack_from(">qqq", buf, off)
            self._health_stats[int(rank)] = (
                int(hseq), int(hsum), _ptime.monotonic())
        elif op == _OP_INIT:
            key, off = _unpack_key(buf, off)
            arr, _ = _unpack_arr(buf, off)
            self._store.setdefault(key, arr)
        elif op == _OP_PUSH:
            key, off = _unpack_key(buf, off)
            grad, _ = _unpack_arr(buf, off)
            if self._updater is not None:
                self._apply(key, grad)
            else:
                self._store[key] = grad.copy()
            self.updates_applied += 1
        elif op == _OP_PUSH_RSP:
            key, off = _unpack_key(buf, off)
            rows_idx, off = _unpack_arr(buf, off)
            rows_val, _ = _unpack_arr(buf, off)
            ids = rows_idx.astype(np.int64)
            if self._updater is not None:
                self._apply_rows(key, ids, rows_val)
            else:
                self._store[key][ids] = rows_val
            self.updates_applied += 1
        elif op == _OP_PUSH_2BIT:
            key, off = _unpack_key(buf, off)
            n, thr = struct.unpack_from(">qd", buf, off)
            off += 16
            words, _ = _unpack_arr(buf, off)
            from .pallas_kernels.compression import dequantize_2bit_jnp
            import jax.numpy as jnp
            from . import storage as _storage_mod
            packed = jnp.asarray(words)
            # same ledger choke point as the live handler: transient
            # dequantize scratch is 'workspace' memory
            _storage_mod.ledger_register(packed, "workspace",
                                         site="kvstore.dequantize")
            grad = np.asarray(dequantize_2bit_jnp(
                packed, int(n), float(thr)))
            grad = grad.reshape(self._store[key].shape)
            if self._updater is not None:
                self._apply(key, grad)
            else:
                self._store[key] = grad.copy()
            self.updates_applied += 1
        elif op == _OP_SET_OPT:
            # the restarted server must re-verify the MAC under ITS
            # pinned secret: a journal written by a peer with a
            # different MXTPU_PS_SECRET is not trusted to unpickle
            mac, blob = buf[off:off + 32], buf[off + 32:]
            if self._secret is None:
                raise RuntimeError(
                    "journaled optimizer but no MXTPU_PS_SECRET")
            want = hmac.new(self._secret, blob,
                            hashlib.sha256).digest()
            if not hmac.compare_digest(mac, want):
                raise PermissionError(
                    "journaled set_optimizer HMAC mismatch")
            import mxnet_tpu.optimizer as opt
            optimizer = pickle.loads(blob)
            self._optimizer = optimizer
            self._updater = opt.get_updater(optimizer)
        elif op == _OP_SNAP_PUT:
            rank, step = struct.unpack_from(">qq", buf, off)
            self._snapshots.put(int(rank), int(step), buf[off + 16:])
        else:
            raise ValueError("unknown journal record %d" % op)

    def _journal_append(self, payload, maybe_compact=False):
        """Durably append one record. ``maybe_compact=True`` is passed
        only by store-mutation handlers that already hold self._lock
        (compaction iterates the store, and the self._lock ->
        _journal_lock nesting order must never reverse)."""
        if self._journal is None:
            return
        with self._journal_lock:
            try:
                self._journal.write(
                    struct.pack(">I", len(payload)) + bytes(payload))
                self._journal_bytes += 4 + len(payload)
            except OSError:
                _profiler.account("kvstore.journal_errors", 1,
                                  emit=False)
                return
            if maybe_compact \
                    and self._journal_bytes >= self._JOURNAL_SEG_BYTES:
                self._journal_compact()

    def _journal_rotate(self):
        # caller holds self._journal_lock
        self._journal.close()
        self._jseq += 1
        self._segment_path = os.path.join(
            self._journal_dir, "seg_%06d.jnl" % self._jseq)
        self._journal = open(self._segment_path, "ab", buffering=0)
        self._journal_bytes = 0

    def _journal_compact(self):
        # caller holds self._lock and self._journal_lock
        if self._updater is not None:
            self._segments.append(os.path.basename(self._segment_path))
            self._journal_rotate()
            return
        tmp = os.path.join(self._journal_dir, "table.tmp")
        with open(tmp, "wb") as f:
            def rec(payload):
                f.write(struct.pack(">I", len(payload)) + payload)
            for key in sorted(self._store, key=str):
                rec(bytes([_J_STORE]) + _pack_key(key)
                    + _pack_arr(np.asarray(self._store[key])))
            rec(struct.pack(">Bq", _J_EPOCH, self._epoch))
            for rank, (hseq, hsum, _at) in sorted(
                    self._health_stats.items()):
                rec(struct.pack(">Bqqq", _J_HEALTH, int(rank),
                                int(hseq), int(hsum)))
            for rank, step, blob in self._snapshots.items():
                rec(struct.pack(">Bqq", _OP_SNAP_PUT, int(rank),
                                int(step)) + bytes(blob))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._journal_dir, "table.snap"))
        done = self._segments + [os.path.basename(self._segment_path)]
        self._segments = []
        self._journal_rotate()
        for n in done:
            try:
                os.remove(os.path.join(self._journal_dir, n))
            except OSError:
                pass
        _profiler.account("kvstore.journal_compactions", 1, emit=False)

    def _seal_journal(self):
        # caller holds self._lock — the same self._lock ->
        # _journal_lock acquisition order as the mutation handlers'
        # _journal_append path, so the runtime lock-order graph stays
        # a straight line
        with self._journal_lock:
            self._journal_compact()
            self._journal.close()
            self._journal = None

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._journal is not None:
            # a CLEAN stop seals the journal into table.snap so the next
            # start replays one snapshot instead of the event history
            # (an abrupt death skips this — that is what replay is for)
            try:
                with self._lock:
                    self._seal_journal()
            except (OSError, ValueError):
                pass


class AsyncPSClient:
    """Worker-side connection (the reference's ps::KVWorker)."""

    def __init__(self, host, port, retries=100, endpoints=None):
        # connection is LAZY: in a sharded group, the server hosted by a
        # higher rank may not exist yet when lower ranks build their
        # client sets — first use retries until it binds (the ps-lite
        # worker's connect-to-server rendezvous)
        self._sock = None
        self._retries = retries
        self._lock = _locktrace.named_lock("kvstore_async.client")
        # ordered failover list (ISSUE 20a): _addr is the CURRENT
        # endpoint; a failed connect walks the cursor to the next one
        self._endpoints = self._resolve_endpoints(host, port, endpoints)
        self._ep_idx = 0
        self.bytes_pushed = 0  # wire accounting (sparse/compressed tests)
        self._hb_stop = None
        # wire trace-context state: what protocol the peer speaks
        # (negotiated per connection) and this client's request counter
        self._peer_version = 0
        self._rank = int(_getenv("MXTPU_PROC_ID", "0") or 0)
        self._req_id = 0
        # fencing-epoch stamp for push ops (0 until a reshard commits a
        # bump through AsyncKVStore.resize; only on the wire when
        # MXTPU_PS_FENCING is enabled)
        self._fence_epoch = 0

    @property
    def _addr(self):
        return self._endpoints[self._ep_idx]

    @staticmethod
    def _resolve_endpoints(host, port, endpoints):
        """The ordered endpoint list this client may fail over across.
        An explicit ``endpoints`` argument wins; else MXTPU_PS_ENDPOINTS
        ("host:port,host:port,...") applies when the constructor address
        is its FIRST entry — the env names the failover chain for the
        primary control-plane endpoint, and sharded-group clients built
        against other servers keep their single address; else the
        constructor address alone (no failover, the pre-ISSUE-20
        wire)."""
        if endpoints:
            return [(h, int(p)) for h, p in endpoints]
        spec = _getenv("MXTPU_PS_ENDPOINTS", "").strip()
        if spec:
            eps = []
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                h, _, p = part.rpartition(":")
                eps.append((h or "127.0.0.1", int(p)))
            if eps and eps[0] == (host, int(port)):
                return eps
        return [(host, int(port))]

    def _failover(self, exc):
        """Advance the endpoint cursor after a failed attempt against
        the current endpoint, counting the failover by reason in
        metrics()['counters'] (kvstore.failovers.<reason>) — the walk
        lives inside the caller's one retry budget, so a dead primary
        costs backoff sleeps against the standby, never a second
        deadline."""
        self._ep_idx = (self._ep_idx + 1) % len(self._endpoints)
        if isinstance(exc, ConnectionRefusedError):
            reason = "refused"
        elif isinstance(exc, (socket.timeout, TimeoutError)):
            reason = "timeout"
        elif isinstance(exc, ConnectionError):
            reason = "reset"
        else:
            reason = "error"
        _profiler.account("kvstore.failovers.%s" % reason, 1,
                          emit=False)

    def _connect_once(self):
        """One connect attempt (the kvstore.connect fault seam); no
        retry of its own — the caller owns the backoff budget, so retry
        loops never nest (a nested budget would multiply the documented
        MXTPU_PS_RETRY_DEADLINE). A fresh connection re-negotiates the
        protocol version with one _OP_HELLO round trip: a v1 server
        answers its version, an old server answers unknown-opcode
        _RE_ERR and the client stays on the v0 (unstamped) wire. With
        MXTPU_PS_RECV_TIMEOUT set the socket carries a recv timeout
        from before the HELLO, so a half-open peer surfaces as a
        counted socket.timeout instead of an indefinite block; a
        transport failure against a multi-endpoint client walks the
        failover cursor before re-raising into the retry loop."""
        if _faultpoint.ACTIVE:
            _faultpoint.check("kvstore.connect")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.connect(self._addr)
            to = float(_getenv("MXTPU_PS_RECV_TIMEOUT", "0") or 0)
            if to > 0:
                sock.settimeout(to)
            _send_frame(sock, struct.pack(">Bq", _OP_HELLO,
                                          _PROTO_VERSION))
            resp = _recv_frame(sock)
        except BaseException as e:
            sock.close()  # no half-open socket per failed attempt
            if isinstance(e, (ConnectionError, OSError)) \
                    and len(self._endpoints) > 1:
                self._failover(e)
            raise
        if resp is None:
            sock.close()
            if len(self._endpoints) > 1:
                self._failover(ConnectionResetError())
            raise ConnectionError(
                "async PS server closed during version negotiation")
        if resp[0] == _RE_INT:
            peer = int(struct.unpack_from(">q", resp, 1)[0])
        else:
            peer = 0  # pre-v1 server: never stamp trace-context
        self._peer_version = min(peer, _PROTO_VERSION)
        self._sock = sock

    def _ensure_connected(self):
        """First-connect rendezvous with the unified backoff policy. The
        attempt budget stays the constructor's ``retries`` (the
        rendezvous with a server that has not bound yet must outlast the
        exponential ramp); base/cap/deadline come from the
        MXTPU_PS_RETRY_* knobs. Reconnects after a broken socket do NOT
        come through here — _call's own retry loop calls _connect_once,
        so the transport deadline is one budget, not a product of two."""
        if self._sock is not None:
            return

        def on_retry(n, exc, delay):
            # connect retries counted apart from mid-stream transport
            # retries and heartbeat failures: three different diagnoses
            _profiler.account("kvstore.connect_retries", 1)

        _retry.call(
            self._connect_once, retryable=(ConnectionError, OSError),
            policy=_retry.RetryPolicy(max_retries=self._retries),
            on_retry=on_retry)


    def start_heartbeat(self, rank, interval=0.5, sync_clock=False,
                        clock_primary=False):
        """Background liveness beats (ref: ps-lite heartbeats feeding
        GetDeadNodes). Returns immediately; stop with stop_heartbeat.
        ``sync_clock=True`` rides a trace-clock timestamp on each beat
        (v1 peers) so the client keeps a live offset estimate against
        this server; ``clock_primary`` marks it the canonical alignment
        target trace merging shifts this rank's shard by."""
        if self._hb_stop is not None:
            return
        import time
        self._hb_stop = threading.Event()

        def run():
            failures = 0
            while not self._hb_stop.is_set():
                try:
                    self.heartbeat(rank, sync_clock=sync_clock,
                                   clock_primary=clock_primary)
                    failures = 0
                    _profiler.account("kvstore.heartbeats", 1,
                                      emit=False)
                except (ConnectionError, OSError, RuntimeError):
                    _profiler.account("kvstore.heartbeat_failures", 1,
                                      emit=False)
                    # a straggler server may not be up yet (lazy
                    # connect): keep beating; give up only after a
                    # sustained outage, loudly
                    failures += 1
                    if failures > 600:
                        warnings.warn(
                            "heartbeat to %s:%d failed %d times; "
                            "liveness tracking stops for this pair"
                            % (*self._addr, failures), RuntimeWarning)
                        return
                self._hb_stop.wait(interval)

        self._hb_thread = threading.Thread(target=run, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_thread.join(timeout=5)
            self._hb_stop = None

    def _call(self, payload, idempotent=True, point="kvstore.send",
              latency=None):
        """One request/response round trip, hardened: a broken socket
        (server restart, dropped connection, injected ``kvstore.send``/
        ``kvstore.pull`` fault) is retried with reconnect + exponential
        backoff under the MXTPU_PS_RETRY_* policy — but only for
        ``idempotent`` requests. init/pull/stats/shape are pure reads or
        idempotent writes; a resent push can at worst double-apply one
        gradient, which async-PS staleness semantics already tolerate
        (kvstore_dist_server.h:358 applies pushes immediately with no
        ordering contract). barrier/done/heartbeat/stop pass
        ``idempotent=False``: re-sending those changes protocol state
        (a double done() inflates the shutdown count; a re-sent barrier
        arrival could release a rendezvous that never happened).

        While a profile run is active and the peer negotiated v1, each
        attempt is stamped with the wire trace-context and the round
        trip becomes a ``ps.client.<op>`` span + flow-start event;
        ``latency`` optionally names the RTT histogram to feed
        (``kvstore.push_rtt`` / ``kvstore.pull_rtt`` /
        ``kvstore.barrier_wait``). Profiling off costs one extra bool
        test and the wire bytes are untouched.

        Budget shape: the patient first-connect rendezvous happens ONCE
        up front; each retry attempt then reconnects with a single
        _connect_once, so the whole operation is bounded by one
        MXTPU_PS_RETRY_DEADLINE, and every backoff sleep runs OUTSIDE
        self._lock (a reconnecting client must not starve its own
        heartbeat thread off the shared lock)."""
        with self._lock:
            self._ensure_connected()

        def attempt():
            with self._lock:
                if self._sock is None:
                    self._connect_once()  # reconnect: caller's budget
                if _faultpoint.ACTIVE:
                    _faultpoint.check(point)
                wire = payload
                t0 = None
                if _profiler._ACTIVE and self._peer_version >= 1:
                    # stamp the negotiated trace-context: fresh req_id
                    # per attempt so a retried send shows up as its own
                    # server span instead of aliasing the lost one
                    self._req_id = next(_REQ_SEQ)
                    t0 = _profiler._now_us()
                    wire = bytes([payload[0] | _TRACE_FLAG]) \
                        + struct.pack(_CTX_FMT, self._rank,
                                      self._req_id, t0) + payload[1:]
                try:
                    _send_frame(self._sock, wire)
                    resp = _recv_frame(self._sock)
                except (ConnectionError, OSError):
                    # mid-stream break: this socket is done either way
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    raise
                if resp is None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    raise ConnectionError(
                        "async PS server closed the connection")
                if t0 is not None:
                    opname = _OP_NAMES.get(payload[0],
                                           "op%d" % payload[0])
                    rtt = _profiler._now_us() - t0
                    _profiler.record_op(
                        "ps.client.%s" % opname, rtt,
                        category="kvstore", lane="kvstore",
                        args={"req_id": self._req_id,
                              "bytes": len(payload)})
                    _profiler.record_flow(
                        "ps.%s" % opname,
                        _flow_id(self._rank, self._req_id), "s",
                        ts_us=t0)
                    if latency is not None:
                        _profiler.record_latency(latency, rtt)
                return resp

        if idempotent:
            def on_retry(n, exc, delay):
                _profiler.account("kvstore.transport_retries", 1,
                                  emit=False)
            resp = _retry.call(attempt,
                               retryable=(ConnectionError, OSError),
                               on_retry=on_retry)
        else:
            resp = attempt()
        code = resp[0]
        if code == _RE_OK:
            return None
        if code == _RE_INT:
            return struct.unpack_from(">q", resp, 1)[0]
        if code == _RE_ARR:
            arr, _ = _unpack_arr(resp, 1)
            return arr
        if code == _RE_BYTES:
            (n,) = struct.unpack_from(">I", resp, 1)
            return resp[5:5 + n]
        if code == _RE_ERR:
            (n,) = struct.unpack_from(">H", resp, 1)
            raise RuntimeError(resp[3:3 + n].decode())
        raise ConnectionError("bad response opcode %d" % code)

    def _fence_tail(self):
        """Trailing ``>q epoch`` stamp for push ops when MXTPU_PS_FENCING
        is on — length-gated: a v0 server's handler never reads past its
        known fields, so the tail is invisible to old peers (the PR 8
        interop idiom, same as the heartbeat's straggler/SDC extras)."""
        if not _fencing_enabled():
            return b""
        return struct.pack(">q", self._fence_epoch)

    def set_fence_epoch(self, epoch):
        """Stamp subsequent fenced pushes with ``epoch`` (committed by
        an elastic reshard via bump_epoch on every server)."""
        self._fence_epoch = int(epoch)

    def bump_epoch(self, proposed=-1):
        """Propose a fencing epoch (the server adopts the max and
        journals the bump); ``-1`` merely queries. Returns the server's
        committed epoch. RuntimeError against a v0 server (unknown
        opcode) — callers count and continue unfenced."""
        return int(self._call(struct.pack(">Bq", _OP_EPOCH,
                                          int(proposed))))

    def preempt_notice(self, rank, step):
        """Announce coordinated preemption (ISSUE 20b): this rank is
        draining after SIGTERM at ``step``. Idempotent (a re-announce
        replaces the slot). RuntimeError against a v0 server — callers
        count and continue; peers then fall back to the heartbeat
        timeout, the pre-ISSUE-20 detection path."""
        self._call(struct.pack(">Bqq", _OP_PREEMPT, int(rank),
                               int(step)))

    def init(self, key, arr):
        self._call(bytes([_OP_INIT]) + _pack_key(key)
                   + _pack_arr(np.asarray(arr)))

    def push(self, key, grad):
        payload = bytes([_OP_PUSH]) + _pack_key(key) \
            + _pack_arr(np.asarray(grad)) + self._fence_tail()
        self.bytes_pushed += len(payload)
        self._call(payload, latency="kvstore.push_rtt")

    def push_row_sparse(self, key, row_ids, rows):
        """Sparse wire: only (row_ids, rows) cross — bytes scale with
        touched rows, not the dense shape."""
        payload = bytes([_OP_PUSH_RSP]) + _pack_key(key) \
            + _pack_arr(np.asarray(row_ids, np.int64)) \
            + _pack_arr(np.asarray(rows)) + self._fence_tail()
        self.bytes_pushed += len(payload)
        self._call(payload, latency="kvstore.push_rtt")

    def push_compressed(self, key, words, n, threshold):
        payload = bytes([_OP_PUSH_2BIT]) + _pack_key(key) \
            + struct.pack(">qd", int(n), float(threshold)) \
            + _pack_arr(np.asarray(words, np.int32)) \
            + self._fence_tail()
        self.bytes_pushed += len(payload)
        self._call(payload, latency="kvstore.push_rtt")

    def pull(self, key):
        return self._call(bytes([_OP_PULL]) + _pack_key(key),
                          point="kvstore.pull",
                          latency="kvstore.pull_rtt")

    def pull_row_sparse(self, key, row_ids):
        return self._call(bytes([_OP_PULL_RSP]) + _pack_key(key)
                          + _pack_arr(np.asarray(row_ids, np.int64)),
                          point="kvstore.pull",
                          latency="kvstore.pull_rtt")

    def shape_of(self, key):
        """Dense shape of a stored key WITHOUT transferring the value
        (row_sparse_pull needs it; a full pull would defeat the sparse
        wire)."""
        arr = self._call(bytes([_OP_SHAPE]) + _pack_key(key))
        return tuple(int(d) for d in arr)

    def barrier(self, num_workers):
        """Block until `num_workers` clients reach this barrier. Runs
        on a DEDICATED connection so the shared one (and the heartbeat
        thread behind its lock) keeps flowing while we wait — a
        barrier-parked worker must not look dead."""
        tmp = AsyncPSClient(*self._addr, endpoints=self._endpoints)
        try:
            # non-idempotent: a resent arrival after a lost response
            # could release a rendezvous that never fully assembled
            tmp._call(struct.pack(">Bq", _OP_BARRIER, int(num_workers)),
                      idempotent=False, latency="kvstore.barrier_wait")
        finally:
            try:
                tmp._sock.close()
            except OSError:
                pass

    def heartbeat(self, rank, sync_clock=False, clock_primary=False):
        # fail-fast (no transport retry): the beat loop re-beats every
        # interval anyway, and its failures are counted DISTINCTLY
        # (kvstore.heartbeat_failures) so a flaky link shows up as such
        # instead of inflating the transport-retry counter
        if sync_clock and self._peer_version >= 1:
            # timestamped beat: client brackets the exchange on its
            # trace clock, the server answers with its own — the
            # NTP-style pair behind merge_traces clock alignment.
            # offset ≈ server_ts - midpoint(t0, t1); error <= rtt/2.
            t0 = _profiler._now_us()
            payload = struct.pack(">Bqd", _OP_HEARTBEAT, int(rank),
                                  float(t0))
            last = _watchdog.last_step()
            hd = _healthmon.shared_digest()
            if last is not None or hd is not None:
                # the per-rank step-duration gauge rides the beat
                # (straggler detection, ISSUE 8): newest completed
                # step's (duration, seq) — a v1 server stores it, an
                # old server's length check ignores the extra bytes.
                # With the watchdog disabled a (0.0, -1) placeholder
                # keeps the fixed offsets so the SDC digest can still
                # ride (seq=-1 = "no step stats": the server skips it)
                dur, seq = (float(last[1]), int(last[0])) \
                    if last is not None else (0.0, -1)
                payload += struct.pack(">dq", dur, seq)
                if hd is not None:
                    # trailing (health seq i64, grad-bucket CRC32 i64):
                    # the SDC gauge (ISSUE 15) — the server leave-one-
                    # out-compares same-seq checksums across ranks;
                    # same length-gated contract as the straggler pair.
                    # shared_digest is non-None only for mesh-DP fused
                    # programs whose grads are bitwise-shared — a
                    # local (single-device / host-reduced) digest
                    # would false-diverge on every healthy step
                    payload += struct.pack(">qq", int(hd[0]),
                                           int(hd[1]))
            arr = self._call(payload, idempotent=False)
            t1 = _profiler._now_us()
            if arr is not None and len(arr):
                _profiler.record_clock_sync(
                    "%s:%d" % self._addr,
                    float(arr[0]) - 0.5 * (t0 + t1), t1 - t0,
                    primary=clock_primary)
            return
        self._call(struct.pack(">Bq", _OP_HEARTBEAT, int(rank)),
                   idempotent=False)

    def dead_nodes(self, timeout=3.0):
        arr = self._call(struct.pack(">Bd", _OP_DEADNODES,
                                     float(timeout)))
        return [int(r) for r in arr]

    def put_snapshot(self, rank, step, blob):
        """Publish this rank's opaque peer-snapshot blob (ISSUE 19c).
        One slot per rank on the server; each publish replaces the
        previous. Raises RuntimeError against a v0 server (unknown
        opcode -> _RE_ERR) — callers treat that as "peer plane
        unavailable" and count, never crash the step."""
        self._call(struct.pack(">Bqq", _OP_SNAP_PUT, int(rank),
                               int(step)) + bytes(blob))

    def get_snapshot(self, exclude_rank, stale_timeout=None):
        """Newest live peer snapshot as ``(rank, step, blob)``, or
        ``None`` when no live peer (heartbeat fresher than
        ``stale_timeout``, default MXTPU_PS_DEAD_TIMEOUT) other than
        ``exclude_rank`` has published one."""
        if stale_timeout is None:
            stale_timeout = float(_getenv("MXTPU_PS_DEAD_TIMEOUT", "3"))
        resp = self._call(struct.pack(">Bqd", _OP_SNAP_GET,
                                      int(exclude_rank),
                                      float(stale_timeout)))
        if not isinstance(resp, (bytes, bytearray, memoryview)):
            return None  # _RE_INT 0: nothing published by a live peer
        resp = bytes(resp)
        rank, step = struct.unpack_from(">qq", resp, 0)
        return int(rank), int(step), resp[16:]

    def profiler_command(self, cmd, body=""):
        c, b = cmd.encode(), body.encode()
        return self._call(bytes([_OP_PROFILER]) + struct.pack(">H", len(c))
                          + c + struct.pack(">H", len(b)) + b)

    def server_metrics(self):
        """The server process's own ``profiler.metrics()`` snapshot
        (the _OP_PROFILER ``metrics`` command): any worker can pull the
        PS server's telemetry — latency histograms, heartbeat-age
        gauges, counters — into its own merged view."""
        import json as _json
        raw = self.profiler_command("metrics")
        return _json.loads(bytes(raw).decode()) if raw else None

    def set_optimizer(self, optimizer):
        secret = _ps_secret()
        if secret is None:
            raise RuntimeError(
                "MXTPU_PS_SECRET is not set; cannot authenticate the "
                "pickled optimizer (serve_if_rank0 generates one — set "
                "it in the launcher env for multi-host runs)")
        blob = pickle.dumps(optimizer)
        mac = hmac.new(secret, blob, hashlib.sha256).digest()
        self._call(bytes([_OP_SET_OPT]) + mac + blob)

    def updates_applied(self):
        return self._call(bytes([_OP_STATS]))

    def done(self, rank=None):
        payload = bytes([_OP_DONE])
        if rank is not None:
            payload += struct.pack(">q", int(rank))
        # non-idempotent: the server COUNTS done() signals, so a resend
        # after a lost response would double-count this worker
        self._call(payload, idempotent=False)

    def wait_done(self, n, timeout=None):
        """Wait until `n` workers called done(); returns True if they
        did before the deadline (default MXTPU_PS_DONE_TIMEOUT, 120s —
        matching the reference's barrier-before-exit patience)."""
        if timeout is None:
            timeout = float(_getenv("MXTPU_PS_DONE_TIMEOUT", "120"))
        reached = self._call(struct.pack(">Bqd", _OP_WAIT_DONE, n,
                                         float(timeout)))
        if not reached:
            warnings.warn(
                "async PS shutdown: %d worker done() signals did not "
                "arrive within %.0fs; stopping anyway" % (n, timeout),
                RuntimeWarning, stacklevel=2)
        return bool(reached)

    def stop_server(self):
        try:
            self._call(bytes([_OP_STOP]), idempotent=False)
        except (ConnectionError, OSError):
            pass


class AsyncKVStore:
    """KVStore-shaped facade over the async PS (the `dist_async` type
    returned by mx.kv.create). Each push is applied server-side
    immediately; pull returns the current (possibly stale) weights —
    the reference's async convergence semantics, not sync's."""

    def __init__(self):
        rank = int(_getenv("MXTPU_PROC_ID", "0"))
        nproc = int(_getenv("MXTPU_NUM_PROCS", "1"))
        self._rank = rank
        self._num_workers = nproc
        self._servers, self._clients = serve_group(rank)
        self._server = self._servers[0] if self._servers else None
        self._client = self._clients[0]  # control plane (barrier etc.)
        self._optimizer = None
        self._done_sent = False
        self._compression = None
        self._compression_bound = int(_getenv(
            "MXNET_KVSTORE_SIZE_LOWER_BOUND", "4096"))
        # dead ranks already reported by dead_nodes(): growth of this
        # set is THE elastic signal (counter + trace marker), so the
        # controller and operators see a rank die exactly once
        self._known_dead = set()
        # committed fencing epoch (ISSUE 20a): bumped on every resize()
        # when MXTPU_PS_FENCING is on, stamped onto every push
        self._fence_epoch = 0
        # dense arrays >= this many elements are SPLIT across the server
        # group (ref: kvstore_dist.h:58 MXNET_KVSTORE_BIGARRAY_BOUND)
        self._bigarray_bound = int(_getenv(
            "MXNET_KVSTORE_BIGARRAY_BOUND", str(1000 * 1000)))
        self._split = {}  # key -> (shape, dtype, [shard lengths])
        self._residuals = {}
        # liveness beats feed each server's dead-node tracking; they
        # also carry the clock-sync timestamps (server 0 = the primary
        # clock every rank's trace shard aligns to in merge_traces)
        hb = float(_getenv("MXTPU_PS_HEARTBEAT_INTERVAL", "0.5"))
        for i, c in enumerate(self._clients):
            c.start_heartbeat(rank, interval=hb, sync_clock=True,
                              clock_primary=(i == 0))
        # Trainer/Module never call done() themselves; signal at process
        # exit so server shutdown never stalls on missing done()s
        # (the reference's Postoffice barrier-before-exit is implicit).
        # weakref: atexit must not pin closed stores for process life
        import atexit
        import weakref
        ref = weakref.ref(self)
        atexit.register(lambda: getattr(ref(), "done", lambda: None)())

    # -- key placement (EncodeDefaultKey semantics) -------------------------
    def _owner(self, key):
        """Stable key -> server index (ref: kvstore_dist.h:263
        EncodeDefaultKey; int-looking keys use modulo like the
        reference, others a stable string hash)."""
        n = len(self._clients)
        if n == 1:
            return 0
        try:
            return int(key) % n
        except (TypeError, ValueError):
            import zlib
            return zlib.crc32(str(key).encode()) % n

    def _shard_lens(self, size):
        n = len(self._clients)
        base, extra = divmod(int(size), n)
        return [base + (1 if i < extra else 0) for i in range(n)]

    @staticmethod
    def _shard_key(key, i):
        return "%s#s%d" % (key, i)

    # identity
    @property
    def type(self):
        return "dist_async"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # data plane
    def init(self, key, value):
        from .kvstore import _ctype_key_value
        from .ndarray.sparse import RowSparseNDArray
        t0 = _ptime.perf_counter() if _profiler._LIVE else None
        nbytes = 0
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            host = vlist[0].asnumpy()
            nbytes += int(host.nbytes)
            if isinstance(vlist[0], RowSparseNDArray):
                # row-sparse params route whole-key (push does too) —
                # splitting would strand the key the RSP push targets
                self._clients[self._owner(k)].init(k, host)
                continue
            if len(self._clients) > 1 \
                    and host.size >= self._bigarray_bound:
                # big-array split: contiguous flat slices, one per
                # server (ref: kvstore_dist.h EncodeDefaultKey big path)
                lens = self._shard_lens(host.size)
                self._split[k] = (host.shape, host.dtype, lens)
                flat = host.ravel()
                off = 0
                for i, ln in enumerate(lens):
                    self._clients[i].init(self._shard_key(k, i),
                                          flat[off:off + ln])
                    off += ln
            else:
                self._clients[self._owner(k)].init(k, host)
        if t0 is not None:
            _profiler.record_op(
                "kvstore_async.init", (_ptime.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": nbytes})

    def push(self, key, value, priority=0):
        from .kvstore import _ctype_key_value
        from .ndarray.sparse import RowSparseNDArray
        import mxnet_tpu.ndarray as nd
        t0 = _ptime.perf_counter() if _profiler._LIVE else None
        nbytes = 0
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            merged = vlist[0] if len(vlist) == 1 else nd.add_n(*vlist)
            # wire-byte accounting is unconditional: the cumulative
            # kvstore.bytes_pushed counter must be trustworthy in
            # production, not only while a profile run is active
            nbytes += int(merged.wire_nbytes if isinstance(
                merged, RowSparseNDArray) else merged.nbytes)
            if isinstance(merged, RowSparseNDArray):
                # row-sparse keys are whole-key routed (the reference
                # splits rows too; documented simplification — lazy
                # .indices/.values are None for dense-built arrays)
                self._clients[self._owner(k)].push_row_sparse(
                    k, merged.indices.asnumpy(),
                    merged.data.asnumpy())
            elif k in self._split:
                flat = merged.asnumpy().ravel()
                jobs = []
                off = 0
                for i, ln in enumerate(self._split[k][2]):
                    jobs.append((i, self._shard_key(k, i),
                                 flat[off:off + ln]))
                    off += ln
                self._fanout(lambda j: self._push_dense(*j), jobs)
            else:
                self._push_dense(self._owner(k), k, merged.asnumpy())
        _profiler.account("kvstore.bytes_pushed", nbytes)
        if t0 is not None:
            _profiler.record_op(
                "kvstore_async.push", (_ptime.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": nbytes})

    def _push_dense(self, cidx, key, host):
        if self._compression is not None \
                and host.size >= self._compression_bound:
            self._push_compressed(cidx, key, host)
        else:
            self._clients[cidx].push(key, host)

    def _push_compressed(self, cidx, key, host):
        """2-bit quantize with per-(shard)key error-feedback residual;
        only the int32 words cross the TCP wire (16x smaller than fp32)
        — the async path has the sync path's wire optimization."""
        import jax.numpy as jnp
        from .pallas_kernels.compression import quantize_2bit_jnp
        thr = self._compression["threshold"]
        flat = jnp.asarray(np.ravel(host), jnp.float32)
        res = self._residuals.get(key)
        if res is None or res.shape != flat.shape:
            res = jnp.zeros_like(flat)
        words, new_res = quantize_2bit_jnp(flat, res, thr)
        # allocation-ledger choke point: the per-key error-feedback
        # residual is long-lived device memory — 'workspace'
        from . import storage as _storage_mod
        _storage_mod.ledger_register(new_res, "workspace",
                                     site="kvstore.residual")
        self._residuals[key] = new_res
        self._clients[cidx].push_compressed(key, np.asarray(words),
                                            flat.shape[0], thr)

    @staticmethod
    def _fanout(fn, jobs):
        """Run one job per server shard concurrently — each client has
        its own socket/lock, so shard transfers overlap instead of
        paying N serialized round trips."""
        if len(jobs) == 1:
            return [fn(jobs[0])]
        results = [None] * len(jobs)
        errors = []

        def run(i, j):
            try:
                results[i] = fn(j)
            # mxlint: disable=MX009 (collected across shard threads; the first error re-raises from the caller after join)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        ts = [threading.Thread(target=run, args=(i, j), daemon=True)
              for i, j in enumerate(jobs)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:
            raise errors[0]
        return results

    def _pull_host(self, k):
        if k in self._split:
            shape, dtype, lens = self._split[k]
            parts = self._fanout(
                lambda i: self._clients[i].pull(self._shard_key(k, i)),
                list(range(len(lens))))
            return np.concatenate(
                [np.ravel(p) for p in parts]).astype(dtype).reshape(shape)
        return self._clients[self._owner(k)].pull(k)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .kvstore import _ctype_key_value
        import jax.numpy as jnp
        assert out is not None
        t0 = _ptime.perf_counter() if _profiler._LIVE else None
        nbytes = 0
        keys, outs = _ctype_key_value(key, out)
        from . import storage as _storage
        for k, olist in zip(keys, outs):
            host = self._pull_host(k)
            nbytes += int(host.nbytes) * len(olist)
            arr = jnp.asarray(host)
            # allocation-ledger choke point (ISSUE 13a): pulled
            # parameter buffers are fresh device memory on the 'io' tag
            _storage.ledger_register(arr, "io", site="kvstore.pull")
            for o in olist:
                o._data = arr
        _profiler.account("kvstore.bytes_pulled", nbytes)
        if t0 is not None:
            _profiler.record_op(
                "kvstore_async.pull", (_ptime.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": nbytes})
        return out

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)
        return out

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out=out)
        return out

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to every server in the group, which
        applies it per push (ref: python/mxnet/kvstore_server.py
        _controller). The blob is HMAC-authenticated on the wire — see
        module docstring."""
        self._optimizer = optimizer
        for c in self._clients:
            c.set_optimizer(optimizer)

    # the rest of the KVStore surface callers touch (Module/Trainer) —
    # same contracts as kvstore.py
    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression on the async TCP wire
        (ref: src/kvstore/gradient_compression.h:38 — the reference
        applies it on the dist wire; async now matches the sync path).
        Pushes of arrays >= MXNET_KVSTORE_BIGARRAY_BOUND elements send
        int32 words with a client-side error-feedback residual."""
        ctype = compression_params.get("type", "2bit")
        if ctype not in ("none", "2bit"):
            raise ValueError("Unsupported compression type %r" % ctype)
        if ctype == "none":
            self._compression = None
            return
        self._compression = {
            "type": "2bit",
            "threshold": float(compression_params.get("threshold", 0.5)),
        }
        # same gating source as the sync path (kvstore.py)
        self._compression_bound = int(compression_params.get(
            "size_lower_bound",
            _getenv("MXNET_KVSTORE_SIZE_LOWER_BOUND", 4096)))

    def set_updater(self, updater):
        raise NotImplementedError(
            "dist_async applies updates server-side; set_optimizer() "
            "ships the optimizer to the server (kvstore_server.py UX)")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle as _p
        from .base import atomic_write
        with atomic_write(fname) as f:
            _p.dump(self._optimizer if dump_optimizer else None, f)

    def load_optimizer_states(self, fname):
        import pickle as _p
        with open(fname, "rb") as f:
            o = _p.load(f)
        if o is not None:
            self.set_optimizer(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows over the wire
        (ref: kvstore.py row_sparse_pull / kvstore_dist.h:522)."""
        from .kvstore import _ctype_key_value
        from .ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray, row_sparse_array
        import jax.numpy as jnp
        assert out is not None and row_ids is not None
        keys, outs = _ctype_key_value(key, out)
        if not isinstance(row_ids, list):
            row_ids = [row_ids] * len(keys)
        for k, olist, rids in zip(keys, outs, row_ids):
            if k in self._split:
                raise NotImplementedError(
                    "row_sparse_pull of a big-array-split key; raise "
                    "MXNET_KVSTORE_BIGARRAY_BOUND or keep row-sparse "
                    "params below it")
            owner = self._clients[self._owner(k)]
            ids = np.asarray(rids.asnumpy()
                             if isinstance(rids, NDArray) else rids,
                             np.int64)
            rows = owner.pull_row_sparse(k, ids)
            full_shape = owner.shape_of(k)  # cheap shape query
            for o in olist:
                if isinstance(o, RowSparseNDArray):
                    new = row_sparse_array((rows, ids), shape=full_shape)
                    o._indices = new._indices
                    o._values = new._values
                    o._data = new._data
                else:
                    dense = np.zeros(full_shape, rows.dtype)
                    dense[ids] = rows
                    densified = jnp.asarray(dense)
                    from . import storage as _storage_mod
                    _storage_mod.ledger_register(
                        densified, "io", site="kvstore.pull_row_sparse")
                    o._data = densified
        return out

    def _barrier(self):
        """Global rendezvous of all workers (ref: MXKVStoreBarrier /
        ps::Postoffice::Barrier)."""
        self._client.barrier(self._num_workers)

    def get_dead_nodes(self, timeout=3.0):
        """Ranks whose heartbeat went stale (ref: ps-lite GetDeadNodes,
        kvstore_dist.h:121). A restarted worker resumes beating and
        drops off this list (is_recovery semantics)."""
        return self.dead_nodes(timeout)

    def dead_nodes(self, timeout=3.0):
        """Client-side dead-node poll (the ``_OP_DEADNODES`` wire op):
        ranks whose heartbeat is staler than ``timeout`` seconds. When
        the set GROWS, each newly-dead rank counts once into
        ``profiler.metrics()['elastic']['dead_rank_detected']`` and
        drops an ``elastic:dead_rank_detected`` instant trace marker —
        the same signal the :class:`~mxnet_tpu.parallel.elastic.
        ElasticController` reshards on, so the controller and operators
        watching the trace/metrics see the failure simultaneously."""
        dead = self._client.dead_nodes(timeout)
        cur = set(dead)
        # a recovered rank (resumed beating: is_recovery semantics)
        # leaves the known set, so a SECOND death re-counts and
        # re-marks instead of being swallowed by the first
        self._known_dead &= cur
        new = sorted(cur - self._known_dead)
        if new:
            self._known_dead.update(new)
            _profiler.bump_elastic("dead_rank_detected", len(new),
                                   args={"ranks": new}, lane="kvstore")
        return dead

    def resize(self, num_workers):
        """Commit an elastic world change: barriers and the shutdown
        rendezvous now wait for ``num_workers`` participants. Called by
        the elastic controller after a reshard so the surviving group
        can still rendezvous (a barrier sized for the old world would
        wait forever on the dead)."""
        num_workers = int(num_workers)
        if num_workers < 1:
            raise ValueError("resize needs >= 1 worker, got %d"
                             % num_workers)
        self._num_workers = num_workers
        if _fencing_enabled():
            # fencing-epoch bump (ISSUE 20a): every elastic reshard
            # commits a new epoch on every server in the group. From
            # here on, a push stamped with the pre-reshard epoch — the
            # signature of a rank partitioned across this commit — is
            # rejected server-side and counted (kvstore.fenced_writes),
            # so split-brain can never corrupt aggregation. The commit
            # adopts the max the group answers (a server that already
            # saw a higher epoch from another survivor wins), and a
            # server that cannot be reached is counted, not fatal: it
            # will adopt the epoch from the next survivor's bump.
            new_epoch = self._fence_epoch + 1
            for c in self._clients:
                try:
                    new_epoch = max(new_epoch, c.bump_epoch(new_epoch))
                except (ConnectionError, OSError, RuntimeError):
                    _profiler.account("kvstore.epoch_bump_failures", 1,
                                      emit=False)
            self._fence_epoch = new_epoch
            for c in self._clients:
                c.set_fence_epoch(new_epoch)

    def announce_preemption(self, step):
        """Broadcast this rank's preemption notice (ISSUE 20b) to every
        server so peers' dead-node polls include it immediately —
        proactive reshard instead of a heartbeat-timeout wait. Never
        raises (the draining rank must reach its checkpoint even when
        the control plane is unreachable); returns how many servers
        acknowledged."""
        acked = 0
        for c in self._clients:
            try:
                c.preempt_notice(self._rank, step)
                acked += 1
            except (ConnectionError, OSError, RuntimeError):
                _profiler.account("kvstore.preempt_notice_failures", 1,
                                  emit=False)
        return acked

    def publish_snapshot(self, step, blob):
        """Publish this rank's opaque training-state blob to the
        control-plane server's peer-snapshot table (ISSUE 19c). The
        blob is built (HMAC-tagged pickle) and later verified by
        ``parallel.elastic`` — this layer moves bytes only. Replaces
        this rank's previous slot; raises RuntimeError against a v0
        server (callers count and continue)."""
        self._client.put_snapshot(self._rank, step, blob)

    def peer_snapshot(self, stale_timeout=None):
        """Newest snapshot a LIVE peer (heartbeat fresher than
        ``stale_timeout``, default MXTPU_PS_DEAD_TIMEOUT) published, as
        ``(rank, step, blob)`` — or ``None`` when no live peer has one.
        This rank's own slot is excluded server-side: recovering from
        your own pre-crash snapshot would resurrect exactly the state
        the failure may have poisoned."""
        return self._client.get_snapshot(self._rank, stale_timeout)

    def set_server_profiler_command(self, cmd, body=""):
        """Forward a profiler command to every PS server process
        (ref: KVStoreServerProfilerCommand, include/mxnet/kvstore.h:49):
        cmd in {'set_config', 'state', 'dump', 'metrics'}."""
        return [c.profiler_command(cmd, body) for c in self._clients]

    def server_metrics(self):
        """Each PS server's own ``profiler.metrics()`` snapshot, in
        server order — the worker-side pull that folds server telemetry
        (its latency histograms, heartbeat ages, error counters) into
        this rank's view of the job."""
        return [c.server_metrics() for c in self._clients]

    def updates_applied(self):
        return sum(c.updates_applied() for c in self._clients)

    def done(self):
        """Signal this worker finished to every server (coordination for
        clean group shutdown — the reference's Postoffice
        barrier-before-exit). Registered atexit, so Trainer/Module exits
        that never call it explicitly still signal."""
        if not self._done_sent:
            self._done_sent = True
            for c in self._clients:
                c.stop_heartbeat()
            for c in self._clients:
                try:
                    c.done(self._rank)
                except (ConnectionError, OSError):
                    pass  # server already gone at interpreter exit

    def close(self):
        # Count our own rank as done so a Trainer/Module exit that never
        # called done() explicitly doesn't stall waiting for itself.
        self.done()
        # server-hosting ranks wait for all workers on THEIR servers
        # (each worker done()s every server), then stop them
        for srv in self._servers:
            cli = AsyncPSClient(srv.bind_host, srv.port)
            cli.wait_done(self._num_workers)
            cli.stop_server()
            srv.stop()


def serve_if_rank0(rank, port_env="MXTPU_ASYNC_PS_PORT"):
    """Back-compat single-server hook: (server-or-None, one client)."""
    servers, clients = serve_group(rank, port_env=port_env)
    return (servers[0] if servers else None), clients[0]


def serve_group(rank, port_env="MXTPU_ASYNC_PS_PORT"):
    """Launcher hook for the SHARDED server group (VERDICT r3 item 6;
    ref: the reference's DMLC_NUM_SERVER server processes +
    EncodeDefaultKey placement, src/kvstore/kvstore_dist.h:263).

    ``MXTPU_NUM_SERVERS`` (default 1) server endpoints exist; in a
    multi-process job rank s < num_servers hosts server s (one server
    thread per designated rank), and in a single process rank 0 hosts
    all of them. Ports are deterministic — coordinator port + 1001 + s
    (DMLC_PS_ROOT_PORT analog) — so every rank can build its client
    set before the servers even bind (clients retry).

    Returns (servers_hosted_here, clients[num_servers]). Servers bind
    the coordinator interface when one is configured (multi-host), else
    loopback — never 0.0.0.0."""
    num_servers = max(1, int(_getenv("MXTPU_NUM_SERVERS", "1")))
    nproc = int(_getenv("MXTPU_NUM_PROCS", "1"))
    coord = _getenv("MXTPU_COORDINATOR", "")
    if coord and ":" in coord:
        host, cport = coord.rsplit(":", 1)
        host = host or "127.0.0.1"
        derived = int(cport) + 1001
        if derived + num_servers > 65536:
            # the launcher's coordinator port is ephemeral and this
            # host's range can run to 65535, so +1001+s can overflow the
            # port space (OverflowError at bind/connect). Wrap the whole
            # derived window back into valid space — deterministically,
            # from the same coordinator port every rank sees, so the
            # group still agrees on the endpoints without talking.
            derived -= 50000
        base = int(_getenv_dynamic(port_env, 0,
                                   family="MXTPU_ASYNC_PS_PORT")) or derived
    else:
        host, base = "127.0.0.1", int(_getenv_dynamic(
            port_env, 0, family="MXTPU_ASYNC_PS_PORT"))
    if rank == 0 and _getenv("MXTPU_PS_SECRET") is None:
        # generated before fork/spawn of local workers; multi-host
        # launchers pass MXTPU_* env through (tools/launch.py)
        os.environ["MXTPU_PS_SECRET"] = _secrets.token_hex(32)
    bind = host if host not in ("127.0.0.1", "localhost") else "127.0.0.1"
    if nproc == 1:
        my_ids = list(range(num_servers)) if rank == 0 else []
    else:
        my_ids = [rank] if rank < num_servers else []
        if num_servers > nproc:
            raise ValueError(
                "MXTPU_NUM_SERVERS=%d > number of processes %d"
                % (num_servers, nproc))
    def _env_key(s):
        return port_env if s == 0 else "%s_%d" % (port_env, s)

    def _derived_port(s):
        """env override first, else deterministic base+s (0 = ephemeral,
        valid only for servers hosted in this process)."""
        return int(_getenv_dynamic(_env_key(s), 0,
                                   family="MXTPU_ASYNC_PS_PORT")) \
            or (base + s if base else 0)

    servers = []
    ports = {}
    for s in my_ids:
        srv = AsyncPSServer(_derived_port(s), bind_host=bind)
        servers.append(srv)
        ports[s] = srv.port

    # publish the ports we actually bound (ephemeral-port flow: workers
    # spawned AFTER the server host inherit these through the env, the
    # pre-sharding serve_if_rank0 contract); hosting overwrites stale
    # values from any earlier in-process group
    for s, p in ports.items():
        os.environ[_env_key(s)] = str(p)
    clients = []
    for s in range(num_servers):
        if s in ports:          # hosted in this process: exact port
            clients.append(AsyncPSClient(bind, ports[s]))
            continue
        p = _derived_port(s)
        if not p:
            raise RuntimeError(
                "cannot discover server %d's port: set %s or run under "
                "tools/launch.py (coordinator port + 1001 + s)"
                % (s, _env_key(s)))
        clients.append(AsyncPSClient(host, p))
    return servers, clients
