"""RecordIO: binary record file format, byte-compatible with dmlc recordio.

TPU-native rewrite of the reference's Python recordio layer
(ref: python/mxnet/recordio.py, dmlc-core recordio format). The format is
kept byte-identical so .rec datasets produced for the reference load here
unchanged: each record is

    uint32 magic = 0xced7230a
    uint32 lrec  = cflag << 29 | length      (cflag: 0 whole, 1/2/3 split)
    data[length] padded to a 4-byte boundary

Like the reference (C++ dmlc::RecordIOWriter behind the C ABI), the fast
path is native: ``src/recordio.cc`` via ctypes (see ``_native.py``),
including dmlc's split-on-embedded-magic writer semantics. A pure-Python
implementation remains as fallback (``MXNET_TPU_NO_NATIVE=1``).
"""
from __future__ import annotations

import collections
import ctypes
import os
import struct

import numpy as np

from . import _native

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "ThreadedRecordReader",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]


class ThreadedRecordReader:
    """Background-thread prefetching record stream over the native library
    (ref: dmlc::ThreadedIter + src/io/iter_prefetcher.h — the C++ producer
    parses records off the Python GIL while the consumer drains a bounded
    ring). Iterable; yields bytes. Requires the native build."""

    def __init__(self, uri, capacity=256, shuffle=False, seed=0):
        if not _native.native_available():
            raise RuntimeError(
                "ThreadedRecordReader requires the native library "
                "(build src/ or unset MXNET_TPU_NO_NATIVE)")
        self._lib = _native.get_lib()
        h = ctypes.c_void_p()
        _native.check_call(self._lib.MXTThreadedReaderCreate(
            uri.encode("utf-8"), capacity, 1 if shuffle else 0, seed,
            ctypes.byref(h)))
        self._h = h

    def _check_open(self):
        if not getattr(self, "_h", None):
            raise ValueError("I/O operation on closed ThreadedRecordReader")

    def read(self):
        self._check_open()
        data = ctypes.c_char_p()
        size = ctypes.c_uint64()
        eof = ctypes.c_int()
        _native.check_call(self._lib.MXTThreadedReaderNext(
            self._h, ctypes.byref(data), ctypes.byref(size),
            ctypes.byref(eof)))
        if eof.value:
            return None
        return ctypes.string_at(data, size.value)

    def reset(self):
        self._check_open()
        _native.check_call(self._lib.MXTThreadedReaderReset(self._h))

    def close(self):
        if getattr(self, "_h", None):
            self._lib.MXTThreadedReaderFree(self._h)
            self._h = None

    def __del__(self):
        self.close()

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

_kMagic = 0xced7230a
_LREC_KIND_BITS = 29
_LREC_LEN_MASK = (1 << _LREC_KIND_BITS) - 1


class _NativeBackend:
    """RecordIO over the C++ library (ref: src/c_api/ MXRecordIO* entries
    → here src/c_api.cc MXTRecord*)."""

    def __init__(self, uri, writable):
        self._lib = _native.get_lib()
        self.writable = writable
        h = ctypes.c_void_p()
        path = uri.encode("utf-8")
        if writable:
            _native.check_call(self._lib.MXTRecordWriterCreate(
                path, ctypes.byref(h)))
        else:
            _native.check_call(self._lib.MXTRecordReaderCreate(
                path, ctypes.byref(h)))
        self._h = h

    def close(self):
        if self._h:
            if self.writable:
                self._lib.MXTRecordWriterFree(self._h)
            else:
                self._lib.MXTRecordReaderFree(self._h)
            self._h = None

    def _check_open(self):
        if not self._h:
            raise ValueError("I/O operation on closed RecordIO file")

    def write(self, buf):
        self._check_open()
        _native.check_call(self._lib.MXTRecordWriterWrite(
            self._h, bytes(buf), len(buf)))

    def read(self):
        self._check_open()
        data = ctypes.c_char_p()
        size = ctypes.c_uint64()
        eof = ctypes.c_int()
        _native.check_call(self._lib.MXTRecordReaderNext(
            self._h, ctypes.byref(data), ctypes.byref(size),
            ctypes.byref(eof)))
        if eof.value:
            return None
        return ctypes.string_at(data, size.value)

    def tell(self):
        self._check_open()
        pos = ctypes.c_uint64()
        fn = self._lib.MXTRecordWriterTell if self.writable \
            else self._lib.MXTRecordReaderTell
        _native.check_call(fn(self._h, ctypes.byref(pos)))
        return pos.value

    def seek(self, pos):
        self._check_open()
        _native.check_call(self._lib.MXTRecordReaderSeek(self._h, pos))


class MXRecordIO:
    """Sequential .rec reader/writer (ref: recordio.py MXRecordIO).
    Uses the native C++ codec when available."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self._backend = None
        self.writable = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError("invalid flag %s" % self.flag)
        if _native.native_available():
            self._backend = _NativeBackend(self.uri, self.writable)
            self.handle = None
        else:
            self._backend = None
            self.handle = open(self.uri, "wb" if self.writable else "rb")
        self.pid = os.getpid()

    def close(self):
        if self._backend is not None:
            self._backend.close()
            self._backend = None
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["_backend"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        flag = "w" if self.writable else "r"
        self.flag = flag
        self.open()

    def _check_pid(self):
        if self._backend is None and self.handle is None:
            raise ValueError("I/O operation on closed RecordIO file")
        # reopen after fork, like the reference's pid check
        if self.pid != os.getpid():
            self.open()

    def reset(self):
        self.close()
        self.open()

    def seek_pos(self, pos):
        """Seek to an absolute byte offset (reader only)."""
        assert not self.writable
        self._check_pid()
        if self._backend is not None:
            self._backend.seek(pos)
        else:
            self.handle.seek(pos)

    def write(self, buf):
        assert self.writable
        self._check_pid()
        if self._backend is not None:
            self._backend.write(buf)
            return
        length = len(buf)
        if length > _LREC_LEN_MASK:
            # 29-bit length field; the native writer throws the same way
            raise IOError("RecordIO record exceeds 2^29-1 bytes")
        # no multi-part splitting: records here are written whole (cflag=0);
        # readers still understand split records produced by dmlc writers
        self.handle.write(struct.pack("<II", _kMagic,
                                      length & _LREC_LEN_MASK))
        self.handle.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def tell(self):
        if self._backend is not None:
            return self._backend.tell()
        return self.handle.tell()

    def read(self):
        assert not self.writable
        self._check_pid()
        if self._backend is not None:
            return self._backend.read()
        parts = []
        magic_bytes = struct.pack("<I", _kMagic)
        while True:
            head = self.handle.read(8)
            if len(head) < 8:
                if parts:
                    raise IOError("truncated split RecordIO record")
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _kMagic:
                raise IOError("invalid RecordIO magic at offset %d"
                              % (self.handle.tell() - 8))
            cflag = lrec >> _LREC_KIND_BITS
            length = lrec & _LREC_LEN_MASK
            data = self.handle.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            if parts:
                # dmlc writers split records wherever the payload contains
                # kMagic, DROPPING those 4 bytes; readers re-insert the magic
                # word between parts (dmlc-core recordio semantics)
                parts.append(magic_bytes)
            parts.append(data)
            # cflag: 0 = complete, 1 = start, 2 = middle, 3 = end
            if cflag in (0, 3):
                return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec with .idx sidecar (ref: MXIndexedRecordIO).
    idx format: "<key>\\t<byte offset>\\n" per record."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.writable:
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = None
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def __getstate__(self):
        d = super().__getstate__()
        d["fidx"] = None
        return d

    def seek(self, idx):
        self.seek_pos(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# header layout for packed image records (ref: recordio.py IRHeader/_IR_FORMAT)
IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a (header, bytes) into a record payload (ref: recordio.py pack).
    flag > 0 means `label` is a float array of that length, stored after the
    fixed header."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        label = float(header.label)
        header = header._replace(label=label)
        return struct.pack(_IR_FORMAT, *header) + s
    label = np.asarray(header.label, dtype=np.float32)
    header = header._replace(flag=label.size, label=0.0)
    return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s


def unpack(s):
    """(header, payload) from a record (ref: recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


# Raw-pixel payload magic: pre-decoded records skip JPEG entirely
# (frombuffer + reshape instead of cv2.imdecode), trading ~13x file
# size for decode-free reads — the .rec fast path for hosts whose CPUs
# cannot keep a chip fed (VERDICT r4 item 8). Layout after the magic:
# u16 height, u16 width, u8 channels, then H*W*C uint8 pixels in HWC
# BGR order (same channel order cv2.imdecode yields, so every consumer
# path is byte-identical from here on). JPEG streams begin FF D8 and
# PNG \x89PNG, so the magic cannot collide.
RAW_MAGIC = b"RAWP"
_RAW_DIMS = struct.Struct("<HHB")


def pack_raw_img(header, img):
    """Pack a pre-decoded uint8 HWC image (BGR, as cv2 reads) with no
    compression — the write side of the raw fast path."""
    img = np.ascontiguousarray(img, dtype=np.uint8)
    if img.ndim != 3:
        raise ValueError("pack_raw_img wants HWC uint8, got shape %s"
                         % (img.shape,))
    h, w, c = img.shape
    return pack(header, RAW_MAGIC + _RAW_DIMS.pack(h, w, c)
                + img.tobytes())


def decode_raw_img(img_bytes):
    """The BGR uint8 HWC view behind a raw payload (zero-copy and
    therefore READ-ONLY — copy before mutating), or None if the
    payload is not raw."""
    if not img_bytes.startswith(RAW_MAGIC):
        return None
    off = len(RAW_MAGIC)
    h, w, c = _RAW_DIMS.unpack_from(img_bytes, off)
    return np.frombuffer(img_bytes, np.uint8,
                         count=h * w * c,
                         offset=off + _RAW_DIMS.size).reshape(h, w, c)


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack (ref: recordio.py pack_img).
    img_fmt=".raw" stores pre-decoded pixels (see pack_raw_img)."""
    import cv2
    if img_fmt == ".raw":
        return pack_raw_img(header, img)
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=1):
    """(header, BGR image array) from a record (ref: recordio.py unpack_img).
    Raw-pixel payloads (pack_raw_img) decode without cv2; they honor
    iscolor like the JPEG path (0 -> 2-D grayscale) and return a
    WRITABLE array (decode_raw_img's zero-copy view is read-only)."""
    header, s = unpack(s)
    raw = decode_raw_img(s)
    if raw is not None:
        if iscolor == 0:
            import cv2
            return header, cv2.cvtColor(raw, cv2.COLOR_BGR2GRAY)
        return header, raw.copy()
    import cv2
    img = cv2.imdecode(np.frombuffer(s, np.uint8), iscolor)
    return header, img
