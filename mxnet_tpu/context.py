"""Device context.

TPU-native re-design of the reference's Context (ref: python/mxnet/context.py,
include/mxnet/base.h Context struct). Devices map onto `jax.devices()`; `tpu()`
is the first-class accelerator, `cpu()` is the host, and `gpu()` is accepted as
an alias for the accelerator so that reference-style scripts written with
``ctx=mx.gpu(0)`` run unchanged on TPU.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus"]


class Context:
    """A device context. ``Context('tpu', 0)`` designates TPU chip 0.

    Unlike the reference there is no per-device thread pool to configure: XLA
    owns scheduling. The context only resolves to a concrete `jax.Device` for
    placement of buffers.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            self.device_type = device_type
            self.device_id = device_id
        if self.device_type not in self.devstr2type:
            raise ValueError("unknown device type %r" % (self.device_type,))

    # -- resolution -------------------------------------------------------
    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    def jax_device(self):
        """Resolve to a concrete jax.Device (accelerator for tpu/gpu, host cpu
        otherwise). Falls back to the default backend if the requested kind is
        absent, so cpu-only CI can still run `tpu()` code. Under a
        multi-process runtime only THIS process's devices are addressable,
        so resolution is over jax.local_devices() (ref: each ps-lite worker
        owning its local GPUs, kvstore_dist.h)."""
        kind = self.device_type
        if kind in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                local = jax.local_devices(backend="cpu")
                return local[min(self.device_id, len(local) - 1)]
            except RuntimeError:
                return jax.local_devices()[0]
        devs = _accel_devices()
        if not devs:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    # -- comparisons / hashing -------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, *args):
        Context._default_ctx.value = self._old_ctx

    def empty_cache(self):
        """ref: Context.empty_cache (python/mxnet/context.py:161). XLA owns the
        HBM pool; this hints the runtime to free donated scratch."""
        # PJRT manages its own BFC pool; nothing to do but keep API parity.
        return None


def _accel_devices():
    for kind in ("tpu", "axon", "gpu"):
        try:
            devs = jax.local_devices(backend=kind)
            if devs:
                return devs
        except RuntimeError:
            continue
    default = jax.local_devices()
    return [d for d in default if d.platform != "cpu"]


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Alias for the accelerator so reference scripts run unchanged."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_gpus():
    return len(_accel_devices())


def num_tpus():
    return len(_accel_devices())


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
