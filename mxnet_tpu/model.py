"""Checkpointing helpers + legacy FeedForward model API.

TPU-native counterpart of python/mxnet/model.py (ref: save_checkpoint
model.py:394, load_checkpoint :442, _create_kvstore :82,
_update_params_on_kvstore :150). Checkpoints use the reference's on-disk
convention: ``prefix-symbol.json`` holds the graph, ``prefix-%04d.params``
holds a dict of NDArrays with ``arg:``/``aux:`` key prefixes, so
Module/Gluon/FeedForward checkpoints all round-trip through one format.
"""
from __future__ import annotations

import collections

from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "FeedForward"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """ref: model.py:82 — resolve a kvstore spec to (kv, update_on_kvstore)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(p.size for p in arg_params.values())
                update_on_kvstore = max_size <= 1024 * 1024 * 16
    else:
        raise TypeError("kvstore must be KVStore, str, or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """ref: model.py:110."""
    for idx, param in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """Server-side optimizer mode (ref: model.py:150): push grad, pull
    updated weight."""
    for index, (w, g) in enumerate(zip(param_arrays, grad_arrays)):
        if g is None:
            continue
        name = param_names[index]
        kvstore.push(name, g, priority=-index)
        kvstore.pull(name, w, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Local optimizer mode (ref: model.py:171): optional kvstore reduce,
    then updater on each device copy (one copy on TPU — DP replicas are
    XLA-sharded, not Python-side copies)."""
    for index, (w, g) in enumerate(zip(param_arrays, grad_arrays)):
        if g is None:
            continue
        if kvstore is not None:
            name = param_names[index]
            kvstore.push(name, g, priority=-index)
            kvstore.pull(name, g, priority=-index)
        updater(index, g, w)


def pack_params(arg_params, aux_params):
    """Build the ``arg:``/``aux:``-prefixed checkpoint dict — the single
    definition of the param-file key convention (ref: model.py:394)."""
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    return save_dict


def unpack_params(save_dict, strict=False):
    """Inverse of pack_params: (arg_params, aux_params)."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        elif strict:
            raise ValueError("invalid param key %r" % (k,))
    return arg_params, aux_params


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """ref: model.py:394. Writes prefix-symbol.json + prefix-%04d.params."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, pack_params(arg_params, aux_params))


def load_params(prefix, epoch):
    """ref: model.py load_params — params only."""
    return unpack_params(nd.load("%s-%04d.params" % (prefix, epoch)))


def load_checkpoint(prefix, epoch):
    """ref: model.py:442 — (symbol, arg_params, aux_params)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy model API (ref: model.py:551 FeedForward — deprecated in the
    reference in favor of Module; provided as a thin veneer over Module for
    script compatibility)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.numpy_batch_size = numpy_batch_size
        self._kwargs = kwargs
        self._module = None

    def _make_module(self, data_names, label_names, work_load_list=None,
                     logger=None):
        from .module import Module
        ctx = self.ctx if isinstance(self.ctx, (list, tuple)) or \
            self.ctx is None else [self.ctx]
        kwargs = {}
        if logger is not None:
            kwargs["logger"] = logger
        if ctx is not None:
            kwargs["context"] = ctx
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names,
                      work_load_list=work_load_list, **kwargs)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            optimizer_params=None):
        train_data = self._as_iter(X, y)
        if eval_data is not None and not hasattr(eval_data, "reset"):
            # (X, y) tuple / arrays, like the reference's _init_eval_iter
            ex, ey = eval_data if isinstance(eval_data, (tuple, list)) \
                else (eval_data, None)
            eval_data = self._as_iter(ex, ey)
        data_names = [d[0] for d in train_data.provide_data]
        label_names = [d[0] for d in train_data.provide_label]
        mod = self._make_module(data_names, label_names,
                                work_load_list=work_load_list, logger=logger)
        mod.fit(train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                monitor=monitor,
                optimizer=self.optimizer,
                optimizer_params=optimizer_params or
                {"learning_rate": self._kwargs.get("learning_rate", 0.01)},
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        test_data = self._as_iter(X, None)
        if self._module is None:
            raise MXNetError("model has not been trained")
        import numpy as _np
        outs = self._module.predict(test_data, num_batch=num_batch,
                                    reset=reset)
        if isinstance(outs, list):
            return [o.asnumpy() for o in outs]
        return outs.asnumpy()

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def _as_iter(X, y):
        from .io import NDArrayIter, DataIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=128)
