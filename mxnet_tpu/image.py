"""mx.image: image decode/resize/augment utilities + ImageIter.

ref: python/mxnet/image/image.py. The reference backs these with C++ OpenCV
ops behind the C ABI (src/io/image_aug_default.cc); here cv2 runs host-side
(decode/augment is host work on TPU too — the chip only sees ready tensors).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from .ndarray import NDArray, array as nd_array

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "ResizeAug", "ForceResizeAug",
           "CenterCropAug", "RandomCropAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "LightingAug",
           "ColorJitterAug", "CreateAugmenter", "Augmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def _np_img(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imread(filename, flag=1, to_rgb=True):
    """ref: image.py imread."""
    cv2 = _cv2()
    img = cv2.imread(filename, cv2.IMREAD_COLOR if flag else
                     cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise IOError("cannot read image %s" % filename)
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[..., None]
    return nd_array(img)


def imdecode(buf, flag=1, to_rgb=True):
    """ref: image.py imdecode (src/io JPEG decode via OpenCV)."""
    cv2 = _cv2()
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().astype(np.uint8)
    img = cv2.imdecode(np.frombuffer(bytes(buf), np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise IOError("cannot decode image buffer")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[..., None]
    return nd_array(img)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    out = cv2.resize(_np_img(src), (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[..., None]
    return nd_array(out)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (ref: image.py resize_short)."""
    img = _np_img(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(img, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = _np_img(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != tuple(size):
        return imresize(img, size[0], size[1], interp)
    return nd_array(img)


def center_crop(src, size, interp=2):
    img = _np_img(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return fixed_crop(img, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_crop(src, size, interp=2):
    img = _np_img(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = _pyrandom.randint(0, max(0, w - cw))
    y0 = _pyrandom.randint(0, max(0, h - ch))
    return fixed_crop(img, x0, y0, min(cw, w), min(ch, h), size, interp), \
        (x0, y0, cw, ch)


def color_normalize(src, mean, std=None):
    img = _np_img(src).astype(np.float32)
    img -= np.asarray(mean, np.float32)
    if std is not None:
        img /= np.asarray(std, np.float32)
    return nd_array(img)


class Augmenter:
    """ref: image.py Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return nd_array(_np_img(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, dtype="float32"):
        super().__init__(type=dtype)
        self.dtype = dtype

    def __call__(self, src):
        return nd_array(_np_img(src).astype(self.dtype))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(np.ravel(mean)), std=list(np.ravel(std)))
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return nd_array(_np_img(src).astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _np_img(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        coef = np.array([0.299, 0.587, 0.114], np.float32)
        gray = (img * coef).sum(-1).mean()
        return nd_array(img * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _np_img(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        coef = np.array([0.299, 0.587, 0.114], np.float32)
        gray = (img * coef).sum(-1, keepdims=True)
        return nd_array(img * alpha + gray * (1 - alpha))


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, 3).astype(np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(-1)
        return nd_array(_np_img(src).astype(np.float32) + rgb)


class RandomGrayAug(Augmenter):
    """Random grayscale conversion (ref: image.py RandomGrayAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]], np.float32)

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            img = _np_img(src).astype(np.float32)
            return nd_array(img @ self.mat)
        return src


class HueJitterAug(Augmenter):
    """Random hue rotation in YIQ space (ref: image.py HueJitterAug,
    approximate linear transform)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], np.float32)
        t = (self.ityiq @ bt @ self.tyiq).T
        img = _np_img(src).astype(np.float32)
        return nd_array(img @ t)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        _pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter list (ref: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.asarray(mean).any():
        auglist.append(ColorNormalizeAug(mean, std if std is not None
                                         else np.ones(3)))
    return auglist


class ImageIter:
    """Python-side flexible image iterator (ref: image.py ImageIter),
    over .rec or .lst+raw images, applying an augmenter list."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, shuffle=False,
                 aug_list=None, label_width=1, **kwargs):
        from .io.io import DataBatch, DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self._shuffle = shuffle
        aug_keys = ("resize", "rand_crop", "rand_resize", "rand_mirror",
                    "mean", "std", "brightness", "contrast", "saturation",
                    "pca_noise", "inter_method")
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in aug_keys})
        self._items = []
        if path_imgrec:
            from .recordio import MXRecordIO, unpack
            rec = MXRecordIO(path_imgrec, "r")
            while True:
                raw = rec.read()
                if raw is None:
                    break
                self._items.append(("rec", raw))
        elif path_imglist:
            import os
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    label = float(parts[1])
                    self._items.append(
                        ("file", (os.path.join(path_root or "", parts[-1]),
                                  label)))
        else:
            raise ValueError("need path_imgrec or path_imglist")
        self.reset()

    @property
    def provide_data(self):
        from .io.io import DataDesc
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        from .io.io import DataDesc
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._order = list(range(len(self._items)))
        if self._shuffle:
            _pyrandom.shuffle(self._order)
        self._cursor = 0

    def __iter__(self):
        return self

    def _load(self, item):
        kind, payload = item
        if kind == "rec":
            from .recordio import unpack
            header, buf = unpack(payload)
            img = imdecode(buf)
            label = header.label
        else:
            fn, label = payload
            img = imread(fn)
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy()
        if arr.ndim == 3 and arr.shape[-1] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        lab = label if np.isscalar(label) or getattr(label, "ndim", 0) == 0 \
            else np.asarray(label).ravel()[0]
        return arr.astype(np.float32), np.float32(lab)

    def next(self):
        from .io.io import DataBatch
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idxs = [self._order[i % n] for i in range(self._cursor, end)]
        pad = max(0, end - n)
        self._cursor = end
        # _load returns (image, label); labels may be scalars
        # (classification) or [N, obj_width] arrays (ImageDetIter)
        imgs, labels = zip(*[self._load(self._items[i]) for i in idxs])
        return DataBatch(data=[nd_array(np.stack(imgs))],
                         label=[nd_array(np.stack(labels))], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()


# detection pipeline members live in image_det.py; resolved lazily so
# the two modules can import in either order (ref: the reference
# re-exports via python/mxnet/image/__init__.py)
_DET_NAMES = ("DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
              "DetHorizontalFlipAug", "DetRandomCropAug",
              "DetRandomPadAug", "CreateMultiRandCropAugmenter",
              "CreateDetAugmenter", "ImageDetIter")


def __getattr__(name):
    if name in _DET_NAMES:
        from . import image_det
        return getattr(image_det, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
