"""Module API: symbolic training interface (ref: python/mxnet/module/).

The reference's layer split (BaseModule -> Module / BucketingModule over
DataParallelExecutorGroup over Executor) is preserved; execution is one XLA
computation per bound graph with GSPMD data parallelism over the module's
contexts.
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["BaseModule", "Module", "BucketingModule",
           "DataParallelExecutorGroup"]
