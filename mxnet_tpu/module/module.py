"""Module: intermediate-level symbolic training API.

TPU-native counterpart of python/mxnet/module/module.py (ref: Module :52,
bind :364, init_params :242, init_optimizer :474, forward :575, backward
:626, update :646, save_checkpoint :165, load :130). One Module owns one
XLA-compiled executor group; data parallelism over its contexts is realised
by GSPMD batch sharding (see executor_group.py) instead of per-device
executor copies, and the update step runs either locally (Updater) or via
the kvstore push/pull contract (ref: python/mxnet/model.py:150).
"""
from __future__ import annotations

import logging

from .. import optimizer as opt
from ..base import MXNetError
from ..context import cpu
from ..initializer import InitDesc, Uniform
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup, _as_desc

__all__ = ["Module"]


class Module(BaseModule):
    """ref: module.py:52."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=cpu(), work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if isinstance(context, (list, tuple)):
            self._context = list(context)
        else:
            self._context = [context]
        self._work_load_list = work_load_list
        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()
        self._compression_params = compression_params

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._grad_req = None

    # -- serialization ------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """ref: module.py:130."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """ref: module.py:165."""
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params,
                        self._aux_params, remove_amp_cast=remove_amp_cast)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    # -- properties ---------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.executor.outputs
        if outs:
            return list(zip(self._output_names, [o.shape for o in outs]))
        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({d.name: d.shape for d in self._label_shapes or []})
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self._output_names, out_shapes))

    # -- parameters ---------------------------------------------------------
    def get_params(self):
        """ref: module.py get_params."""
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    _DEFAULT_INIT = object()

    def init_params(self, initializer=_DEFAULT_INIT, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """ref: module.py:242 (signature default Uniform(0.01) there, so
        params absent from arg_params/aux_params still get initialized —
        while set_params' explicit initializer=None disables fallback)."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is Module._DEFAULT_INIT:
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                n: self._exec_group.executor.arg_dict[n]
                for n in self._param_names
                if n in self._exec_group.executor.arg_dict}
        if self._aux_params is None:
            self._aux_params = dict(self._exec_group.executor.aux_dict)

        var_attrs = self._symbol.attr_dict

        def _impl(name, arr, cache):
            # mirrors the reference's _impl (module.py:267): cached value
            # wins; a missing name raises unless allow_missing, in which
            # case (and when no cache was given at all) the initializer runs
            if cache is not None:
                if name in cache:
                    src = cache[name]
                    if src is not arr:
                        arr._data = src._data.astype(
                            arr._data.dtype).reshape(arr.shape)
                    return
                if not allow_missing:
                    raise RuntimeError("%s is not presented" % name)
            if initializer is not None:
                # variable attrs carry per-param init overrides (__init__)
                initializer(InitDesc(name, attrs=var_attrs.get(name)), arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=True)

    def _sync_params_from_devices(self):
        """ref: module.py _sync_params_from_devices. Buffers are shared with
        the executor, so this only refreshes the dict views."""
        if not self.binded or not self.params_initialized:
            return
        exe = self._exec_group.executor
        for n in self._param_names:
            if n in exe.arg_dict:
                self._arg_params[n] = exe.arg_dict[n]
        for n, v in exe.aux_dict.items():
            self._aux_params[n] = v
        self._params_dirty = False

    # -- binding ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: module.py:364."""
        if force_rebind:
            self._exec_group = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = _as_desc(data_shapes)
        self._label_shapes = _as_desc(label_shapes) if label_shapes else []

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group=shared_group,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names)
        self.binded = True

        if shared_module is not None and shared_module.params_initialized:
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self.params_initialized = True
        elif self.params_initialized:
            # load() path: params arrived before bind
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)
            self._arg_params = {
                n: self._exec_group.executor.arg_dict[n]
                for n in self._param_names
                if n in self._exec_group.executor.arg_dict}
            self._aux_params = dict(self._exec_group.executor.aux_dict)

    def reshape(self, data_shapes, label_shapes=None):
        """ref: module.py reshape — switch executors on new shapes, keeping
        parameters. Executor groups are cached per shape signature (like
        BucketingModule's per-bucket executors) so alternating batch
        geometries — e.g. a smaller last batch every epoch — reuse the
        already-compiled XLA programs instead of retracing."""
        assert self.binded
        arg_params, aux_params = (self._arg_params, self._aux_params) \
            if self.params_initialized else (None, None)
        if self.params_initialized:
            self._sync_params_from_devices()
        old_group = self._exec_group

        if not hasattr(self, "_exec_cache"):
            # LRU-bounded: workloads that reshape to many distinct
            # geometries must not retain every compiled executor forever
            from collections import OrderedDict
            self._exec_cache = OrderedDict()
        curr_key = (tuple((d.name, tuple(d.shape))
                          for d in self._data_shapes),
                    tuple((d.name, tuple(d.shape))
                          for d in self._label_shapes or []))
        self._exec_cache[curr_key] = old_group
        self._exec_cache.move_to_end(curr_key)

        new_data = _as_desc(data_shapes)
        new_label = _as_desc(label_shapes) if label_shapes else []
        new_key = (tuple((d.name, tuple(d.shape)) for d in new_data),
                   tuple((d.name, tuple(d.shape)) for d in new_label))
        cached = self._exec_cache.get(new_key)
        if cached is not None:
            self._exec_group = cached
            self._exec_cache.move_to_end(new_key)
            self._data_shapes = new_data
            self._label_shapes = new_label
        else:
            self.binded = False
            self._exec_group = None
            self.bind(data_shapes, label_shapes,
                      for_training=self.for_training,
                      inputs_need_grad=self.inputs_need_grad,
                      force_rebind=True, grad_req=self._grad_req or "write")
            self._exec_cache[new_key] = self._exec_group
        while len(self._exec_cache) > 8:
            self._exec_cache.popitem(last=False)
        if arg_params is not None:
            self._exec_group.set_params(arg_params, aux_params,
                                        allow_extra=True)
            self._sync_params_from_devices()
            self.params_initialized = True
        if old_group is not None and self._exec_group is not old_group \
                and self._grad_req == "add":
            # carry accumulated parameter gradients across the switch
            old_g = old_group.executor.grad_dict
            new_g = self._exec_group.executor.grad_dict
            for n, g in old_g.items():
                tgt = new_g.get(n)
                if tgt is not None and tgt.shape == g.shape:
                    tgt._data = g._data

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """ref: module.py:474."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        kv, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kv and "dist" in kv.type and "_sync" in kv.type:
            batch_size *= kv.num_workers
        rescale_grad = 1.0 / batch_size
        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._param_names))
            optimizer_params = dict(optimizer_params)
            # normalize the batch-summed gradient unless the caller chose
            # their own scale (ref: module.py:498 init_optimizer)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
            # per-variable __lr_mult__/__wd_mult__ attrs (sym.Variable
            # lr_mult=...) flow into the optimizer like the reference's
            # attr_dict wiring (ref: module.py:502 init_optimizer)
            attrs = self._symbol.attr_dict
            lr_mult = {n: float(a["__lr_mult__"])
                       for n, a in attrs.items() if "__lr_mult__" in a}
            wd_mult = {n: float(a["__wd_mult__"])
                       for n, a in attrs.items() if "__wd_mult__" in a}
            if lr_mult:
                optimizer.set_lr_mult(lr_mult)
            if wd_mult:
                optimizer.set_wd_mult(wd_mult)
        else:
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size "
                    "(%s vs. %s). Is this intended?",
                    optimizer.rescale_grad, rescale_grad)
        self._optimizer = optimizer
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kv is not None:
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kv,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """ref: module.py borrow_optimizer (BucketingModule support)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # -- computation --------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """ref: module.py:575. Reshapes on the fly if the batch geometry
        changed (last-batch handling), like the reference."""
        assert self.binded and self.params_initialized
        curr = {d.name: d.shape for d in self._data_shapes}
        new_shapes = {}
        for desc, arr in zip(self._data_shapes, data_batch.data):
            shape = tuple(arr.shape)
            if curr[desc.name] != shape:
                new_shapes[desc.name] = shape
        if new_shapes:
            new_data = [(d.name, new_shapes.get(d.name, d.shape))
                        for d in self._data_shapes]
            new_label = None
            if self._label_shapes and getattr(data_batch, "label", None):
                new_label = [(d.name, tuple(a.shape)) for d, a in
                             zip(self._label_shapes, data_batch.label)]
            self.reshape(new_data, new_label)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        """ref: module.py:626."""
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """ref: module.py:646 → model.py:150/171."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore, self._param_names)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels)

    # -- optimizer state ----------------------------------------------------
    def save_optimizer_states(self, fname):
        """ref: module.py save_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        """ref: module.py load_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updater.set_states(fin.read())

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)
