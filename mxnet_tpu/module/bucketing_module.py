"""BucketingModule: variable-length sequence training via per-bucket graphs.

TPU-native counterpart of python/mxnet/module/bucketing_module.py (ref:
BucketingModule :40, switch_bucket :362). Buckets are the reference's (and
XLA's) answer to dynamic shapes: one compiled program per bucket geometry,
parameters shared across buckets. Here each bucket is a Module whose
executor shares parameter buffers with the default bucket's executor
(shared_module), so switching buckets costs one jit-cache lookup after the
first compile — the XLA analog of the reference's shared memory pool between
bucket executors.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..context import cpu
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """ref: bucketing_module.py:40."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=cpu(), work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._compression_params = compression_params
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._monitor = None
        self._grad_req = None

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def get_params(self):
        assert self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=Module._DEFAULT_INIT, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: bucketing_module.py bind — binds the default bucket."""
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._buckets = {}
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True

        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names,
                        logger=self.logger, context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        compression_params=self._compression_params)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref: bucketing_module.py:362."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            compression_params=self._compression_params)
            module.bind(data_shapes, label_shapes, self._curr_module.
                        for_training, self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key],
                        grad_req=self._grad_req or "write")
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        """ref: bucketing_module.py forward — switches to the batch's
        bucket."""
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels,
                                        pre_sliced=pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """ref: bucketing_module.py save_checkpoint (default bucket's
        symbol + shared params)."""
        assert self.binded
        default_mod = self._buckets[self._default_bucket_key]
        arg, aux = self.get_params()
        from ..model import save_checkpoint as _save
        _save(prefix, epoch, default_mod.symbol, arg, aux)
        if save_optimizer_states:
            self._curr_module.save_optimizer_states(
                "%s-%04d.states" % (prefix, epoch))
