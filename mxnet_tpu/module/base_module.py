"""BaseModule: the high-level symbolic training interface.

TPU-native counterpart of python/mxnet/module/base_module.py (ref:
BaseModule :63, fit :409, score :176, predict :312, forward_backward :193).
The intermediate/low-level API split (bind / init_params / init_optimizer /
forward / backward / update) is preserved so reference training scripts run
unchanged; underneath, one XLA computation per module replaces the
per-device executor interpretation.
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from .. import metric as _metric
from ..base import MXNetError
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _check_input_names(symbol, names, typename, throw):
    """ref: base_module.py:33."""
    args = symbol.list_arguments() + symbol.list_auxiliary_states()
    for name in names:
        if name not in args:
            msg = "You created Module with Module(..., %s_names=%s) but " \
                  "input with name '%s' is not found in symbol.list_" \
                  "arguments()." % (typename, names, name)
            if throw:
                raise ValueError(msg)
            logging.warning(msg)


def _as_metric(eval_metric):
    if isinstance(eval_metric, _metric.EvalMetric):
        return eval_metric
    return _metric.create(eval_metric)


class BaseModule:
    """ref: base_module.py:63."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- abstract surface (implemented by Module/BucketingModule) ----------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    # -- shared conveniences -----------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def forward_backward(self, data_batch):
        """ref: base_module.py:193."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """ref: base_module.py set_params."""
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        """ref: base_module.py save_params."""
        from .. import ndarray as nd
        from ..model import pack_params
        arg_params, aux_params = self.get_params()
        nd.save(fname, pack_params(arg_params, aux_params))

    def load_params(self, fname):
        """ref: base_module.py load_params."""
        from .. import ndarray as nd
        from ..model import unpack_params
        try:
            arg_params, aux_params = unpack_params(nd.load(fname),
                                                   strict=True)
        except ValueError:
            raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    # -- evaluation ---------------------------------------------------------
    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """ref: base_module.py:176."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """ref: base_module.py iter_predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outputs = [out[0:out.shape[0] - pad]
                       for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """ref: base_module.py:312."""
        from .. import ndarray as nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise ValueError("Cannot merge batches: different "
                                     "numbers of outputs per batch")
            output_list2 = [nd.concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    # -- training loop ------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """ref: base_module.py:409 — the canonical symbolic training loop."""
        from ..initializer import Uniform
        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                try:
                    next_data_batch = next(data_iter)
                    self.prepare(next_data_batch,
                                 sparse_row_id_fn=sparse_row_id_fn)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, toc - tic)

            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    # -- misc ---------------------------------------------------------------
    def prepare(self, data_batch, sparse_row_id_fn=None):
        """ref: base_module.py prepare — row-sparse pull hook; dense TPU
        storage needs no per-batch row fetch."""

    def install_monitor(self, mon):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
