"""Data-parallel execution group.

TPU-native counterpart of DataParallelExecutorGroup
(ref: python/mxnet/module/executor_group.py:144, decide_slices :282). The
reference creates one Executor per GPU, slices each batch across them on the
host, and reduces gradients through kvstore. On TPU the idiomatic design is
the opposite: ONE compiled executor whose inputs are laid out over a
`jax.sharding.Mesh` of the bound contexts with the batch axis sharded —
XLA/GSPMD partitions the single program and inserts the gradient
all-reduce on ICI, replacing both the host-side slicing loop and the
kvstore reduce. `decide_slices` is kept because BucketingModule and user
code consult it for workload partitioning.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from ..parallel.compat import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..executor import Executor
from ..io import DataDesc
from ..ndarray import NDArray

__all__ = ["DataParallelExecutorGroup"]


def _as_desc(shapes):
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            out.append(DataDesc(name, tuple(shape)))
    return out


class DataParallelExecutorGroup:
    """One XLA-partitioned executor over the contexts' device mesh."""

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = list(contexts)
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.data_shapes = _as_desc(data_shapes)
        self.label_shapes = _as_desc(label_shapes)
        self.batch_size = self.data_shapes[0].shape[0]
        self.slices = self.decide_slices(self.data_shapes)

        devices = []
        for c in self.contexts:
            d = c.jax_device()
            if d not in devices:
                devices.append(d)
        self._mesh = None
        if len(devices) > 1:
            self._mesh = Mesh(_np.array(devices), ("dp",))

        input_names = {d.name for d in self.data_shapes}
        input_names |= {d.name for d in self.label_shapes}
        self._input_names = input_names

        arg_names = symbol.list_arguments()
        req = {}
        for name in arg_names:
            if name in input_names:
                req[name] = "write" if (inputs_need_grad and
                                        name not in
                                        {d.name for d in self.label_shapes}) \
                    else "null"
            elif name in self.fixed_param_names or not for_training:
                req[name] = "null"
            else:
                req[name] = grad_req if isinstance(grad_req, str) else \
                    grad_req.get(name, "write")
        shapes = {d.name: d.shape for d in self.data_shapes}
        shapes.update({d.name: d.shape for d in self.label_shapes})

        if shared_group is not None:
            # share parameter buffers with the donor group (BucketingModule;
            # ref: executor_group.py shared_group / CachedOp param sharing)
            donor = shared_group.executor
            args = {}
            arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
            for n, s in zip(arg_names, arg_shapes):
                if n in donor.arg_dict and tuple(
                        donor.arg_dict[n].shape) == tuple(s):
                    args[n] = donor.arg_dict[n]
                else:
                    args[n] = NDArray(jnp.zeros(s, _np.float32))
            aux = {}
            for n, s in zip(symbol.list_auxiliary_states(), aux_shapes):
                if n in donor.aux_dict and tuple(
                        donor.aux_dict[n].shape) == tuple(s):
                    aux[n] = donor.aux_dict[n]
                else:
                    aux[n] = NDArray(jnp.zeros(s, _np.float32))
            grads = {n: NDArray(jnp.zeros_like(args[n]._data))
                     for n in arg_names
                     if req.get(n, "null") != "null"
                     and _np.issubdtype(args[n].dtype, _np.inexact)}
            self.executor = Executor(symbol, self.contexts[0], args=args,
                                     args_grad=grads, grad_req=req,
                                     aux_states=aux)
        else:
            self.executor = Executor.simple_bind(
                symbol, self.contexts[0], grad_req=req, **shapes)
        self.execs = [self.executor]   # reference exposes one per device

    def decide_slices(self, data_shapes):
        """Per-context batch ranges (ref: executor_group.py:282). On TPU the
        split is realised by GSPMD sharding, but the ranges are still the
        contract for workload partitioning."""
        n = len(self.contexts)
        bs = data_shapes[0].shape[0]
        step = (bs + n - 1) // n
        slices = []
        start = 0
        for _ in range(n):
            stop = min(start + step, bs)
            slices.append(slice(start, stop))
            start = stop
        return slices

    def _shard(self, value):
        if self._mesh is None:
            return value
        spec = P("dp") if value.ndim >= 1 and \
            value.shape[0] % self._mesh.size == 0 else P()
        return jax.device_put(value, NamedSharding(self._mesh, spec))

    # -- data movement ------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for desc, arr in zip(self.data_shapes, data_batch.data):
            feeds[desc.name] = arr
        if self.label_shapes and getattr(data_batch, "label", None):
            for desc, arr in zip(self.label_shapes, data_batch.label):
                feeds[desc.name] = arr
        for name, arr in feeds.items():
            data = arr._data if isinstance(arr, NDArray) else jnp.asarray(
                _np.asarray(arr))
            tgt = self.executor.arg_dict[name]
            data = data.astype(tgt._data.dtype)
            if data.shape != tgt.shape:
                raise MXNetError(
                    "shape mismatch for %r: got %s, bound %s"
                    % (name, data.shape, tgt.shape))
            tgt._data = self._shard(data)
        self.executor.forward(is_train=is_train)

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to call backward")
        self.executor.backward(out_grads=out_grads)

    # -- views --------------------------------------------------------------
    def get_outputs(self, merge_multi_context=True):
        return list(self.executor.outputs)

    def get_params(self, arg_params, aux_params):
        for n in self.param_names:
            if n in self.executor.arg_dict:
                arg_params[n] = self.executor.arg_dict[n].copy()
        for n, v in self.executor.aux_dict.items():
            aux_params[n] = v.copy()

    def set_params(self, arg_params, aux_params, allow_extra=False):
        self.executor.copy_params_from(arg_params, aux_params,
                                       allow_extra_params=allow_extra)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self.executor.grad_dict.get(d.name)
                for d in self.data_shapes]

    @property
    def grad_arrays(self):
        """grads in param_names order (None where grad_req='null')."""
        return [self.executor.grad_dict.get(n) for n in self.param_names]

    @property
    def param_arrays(self):
        return [self.executor.arg_dict[n] for n in self.param_names
                if n in self.executor.arg_dict]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        mon.install(self.executor)
