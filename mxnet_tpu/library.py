"""Library management API (ref: python/mxnet/library.py).

Thin alias of :mod:`mxnet_tpu.lib_api` so reference code using
``mx.library.load(path)`` works unchanged.
"""
from .lib_api import load, loaded_libraries  # noqa: F401

__all__ = ["load", "loaded_libraries"]
