"""Profiler: per-op tracing + user Domains/Tasks/Counters/Events.

TPU-native re-design of the reference profiler (ref: python/mxnet/profiler.py,
src/profiler/profiler.h:251, src/profiler/aggregate_stats.cc). The reference
hooks every engine OprBlock; here the analog is twofold:

* **Device-side**: when a profile run is active we start a ``jax.profiler``
  trace (xprof) so XLA:TPU emits per-HLO timing — the TPU equivalent of the
  engine's per-op ProfileOperator hooks.
* **Host-side**: an in-process event recorder mirrors the reference's
  chrome://tracing JSON dump (``DumpProfile``, profiler.h:299) and aggregate
  table (``dumps``, aggregate_stats.cc), and backs the user-facing
  Domain/Task/Frame/Event/Counter/Marker objects
  (ref: python/mxnet/profiler.py:226-491).

The host trace is organized into stable **lanes** (chrome-trace tid rows
named via ``thread_name`` metadata, ≙ the reference's per-device/per-thread
profiling domains, profiler.h:120 DeviceStats): ``imperative`` (op dispatch),
``bulk`` (segment flushes), ``kvstore`` (push/pull/init + wire counters),
``io`` (prefetch spans + queue depth), ``autograd`` (backward sweeps),
``memory`` (per-device HBM counters), ``gluon`` (Trainer.step), and ``user``
(Domain/Task/... objects). Subsystems emit through ``record_op`` /
``record_counter`` / ``account`` and guard on ``profiler._ACTIVE`` first, so
everything is zero-cost when profiling is off.

``profile_memory`` samples ``storage.stats()`` (PJRT per-device
bytes_in_use/peak) on a background thread plus at bulk-flush boundaries —
the analog of the reference pool counters feeding MemoryProfiler.
``continuous_dump``/``dump_period`` rewrite the trace file atomically every
period (ref: MXSetContinuousProfileDump) so long runs are inspectable
mid-flight. ``metrics()`` returns the whole surface as one JSON-safe dict.

Distributed observability plane (ISSUE 6): every event carries
``pid=rank`` so per-rank trace shards merge into one chrome trace
(``merge_traces`` / ``tools/trace_merge.py``), aligned via the clock
offsets the kvstore heartbeat path measures (``record_clock_sync``);
``record_latency`` feeds log-bucketed histograms with
p50/p95/p99 in ``metrics()['latency']``; ``record_flow`` emits the
chrome flow events (``ph:"s"/"f"``) that pair a client request span with
the server-side handling span across processes; and ``serve_metrics``
exposes the whole snapshot as a zero-dependency Prometheus ``/metrics``
HTTP endpoint (``MXNET_PROFILER_HTTP_PORT``).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time

from ._debug import flightrec as _flightrec
from ._debug import locktrace as _locktrace
from .base import getenv as _getenv

__all__ = [
    "set_config", "set_state", "dump", "dumps", "pause", "resume",
    "Domain", "Task", "Frame", "Event", "Counter", "Marker",
    "record_op", "record_counter", "account", "sample_memory", "metrics",
    "is_running", "imperative_stats", "reset_imperative_stats", "LANES",
    "register_stats_provider", "record_latency", "record_flow",
    "record_clock_sync", "clock_sync", "latency_metrics",
    "serve_metrics", "stop_metrics_server", "prometheus_text",
    "merge_traces", "PID",
    "marker", "bump_elastic", "elastic_stats", "reset_elastic_stats",
    "record_compile", "compile_stats", "ensure_lane",
    "record_program", "program_records",
]

# chrome-trace pid of every event this process emits: the worker rank.
# Per-rank trace shards then merge into ONE job-wide trace with each
# rank as its own process row (merge_traces / tools/trace_merge.py).
PID = int(_getenv("MXTPU_PROC_ID", "0") or 0)

# Stable pid/tid lanes of the host trace. tid doubles as the sort index.
LANES = {
    "imperative": 0,
    "bulk": 1,
    "kvstore": 2,
    "io": 3,
    "autograd": 4,
    "memory": 5,
    "gluon": 6,
    "user": 7,
    "compile": 8,
    "health": 9,
}

# dynamic lanes (ensure_lane) are allocated from here up, so the fixed
# rows above keep their stable sort indices even as subsystems add rows
_DYN_LANE_BASE = 16


def ensure_lane(name, base=None):
    """Allocate (or return) a stable trace tid for a *dynamic* lane —
    e.g. one trace row per decode-pool worker (``io.w0``, ``io.w1``,
    ...). Idempotent: the first caller wins the tid, every later call
    returns it, and the lane shows up in the trace's thread_name
    metadata like the built-in rows. Dynamic tids start at
    ``_DYN_LANE_BASE`` so the fixed lanes keep their sort order."""
    floor = _DYN_LANE_BASE if base is None else int(base)
    with _lock:
        tid = LANES.get(name)
        if tid is None:
            tid = max(max(LANES.values()) + 1, floor)
            LANES[name] = tid
        return tid

_lock = _locktrace.named_lock("profiler.events")
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "aggregate_stats": False,
    "profile_memory": False,
    "continuous_dump": False,
    "dump_period": 1.0,
    "xprof": True,
    "xprof_dir": None,
    "xprof_active": False,
}
# Fast-path guard mirrored from (running and not paused). Subsystem hooks
# read this module attribute before building any event dict — the
# profiling-off cost of the whole telemetry layer is this one truth test
# (BENCH_MODEL=profiler_overhead keeps it honest).
_ACTIVE = False
# The SHARED hot-path guard (ISSUE 8): true when a profile run is
# active OR the always-on flight recorder wants span feeds. Hot call
# sites guard on `_HOOKS and _profiler._LIVE` — ONE inlined truth test
# covers both consumers (mxlint MX002/MX010/MX011), and record_op /
# record_counter / marker / account internally fan out to the flight-
# recorder ring before gating trace emission on _ACTIVE. Maintained by
# _update_live() from set_state/pause/resume and flightrec.enable/
# disable.
_LIVE = _flightrec.ENABLED


def _update_live():
    global _LIVE
    _LIVE = _ACTIVE or _flightrec.ENABLED

_events = []          # chrome-trace event dicts
_agg = {}             # name -> [count, total_us, min_us, max_us]
_counters = {}        # cumulative subsystem counters (kvstore/io bytes, ...)
_mem_last = {}        # str(device) -> last sampled memory dict
# name -> [count, sum_us, min_us, max_us, {bucket_idx: count}] — the
# log-bucketed latency histograms behind record_latency()
_latency = {}
# peer -> {"offset_us", "rtt_us", "samples", "primary"}: clock-offset
# estimates from the kvstore heartbeat path (min-RTT sample wins); the
# trace-merge CLI reads these out of each shard's metadata block
_clock_sync = {}
_t0 = time.perf_counter()

# Trace-event cap: a multi-hour run with the 10Hz memory sampler + per-op
# spans must not grow _events (and the continuous-dump serialization of
# it) without bound. Aggregate/counter totals keep counting past the cap;
# only raw timeline events are dropped, tallied in
# counters['profiler.dropped_events'].
_MAX_EVENTS = int(_getenv("MXNET_PROFILER_MAX_EVENTS", "1000000"))
# serializes trace-file writers (continuous-dump daemon vs explicit
# dump()): both write the same temp path, and interleaved writers would
# break the atomic-rewrite guarantee
_dump_lock = _locktrace.named_lock("profiler.dump")


def _append_locked(ev):
    """Append one trace event; caller holds _lock. Drops (and tallies)
    events past _MAX_EVENTS so unbounded runs stay bounded."""
    # mxlint: disable=MX014 (telemetry side channel: the cap gates what gets RECORDED, never a value that flows into a traced graph)
    if len(_events) >= _MAX_EVENTS:
        # mxlint: disable=MX003 (caller holds _lock — the function's contract, see docstring)
        _counters["profiler.dropped_events"] = \
            _counters.get("profiler.dropped_events", 0) + 1
        return
    # mxlint: disable=MX003 (caller holds _lock — the function's contract, see docstring)
    _events.append(ev)


_mem_thread = None
_dump_thread = None
_threads_stop = None

_VALID_CONFIG_KEYS = frozenset((
    "filename", "aggregate_stats", "profile_memory", "continuous_dump",
    "dump_period", "xprof", "xprof_dir", "profile_all", "profile_symbolic",
    "profile_imperative", "profile_api", "profile_process",
))


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def set_config(**kwargs):
    """Configure the profiler (ref: python/mxnet/profiler.py:33
    MXSetProcessProfilerConfig). Accepted keys: ``filename``,
    ``profile_all/profile_symbolic/profile_imperative/profile_api``
    (accepted for parity; host+device tracing is unified here),
    ``profile_memory`` (background HBM sampling into the ``memory`` lane),
    ``aggregate_stats``, ``continuous_dump``/``dump_period`` (atomic
    periodic trace rewrite), ``profile_process``, and TPU-specific
    ``xprof`` (bool: start a device trace, default True) / ``xprof_dir``
    (directory for it; defaults next to ``filename``).

    The whole kwargs dict is validated before ANY of it is applied, so a
    bad call can never leave the config half-mutated."""
    if not set(kwargs) <= _VALID_CONFIG_KEYS:
        bad = sorted(set(kwargs) - _VALID_CONFIG_KEYS)
        raise ValueError("unknown profiler config key%s %s"
                         % ("s" if len(bad) > 1 else "", ", ".join(
                             repr(k) for k in bad)))
    if "dump_period" in kwargs:
        period = float(kwargs["dump_period"])
        if period <= 0:
            raise ValueError("dump_period must be > 0, got %r"
                             % (kwargs["dump_period"],))
        kwargs["dump_period"] = period
    if "filename" in kwargs and not isinstance(kwargs["filename"], str):
        raise ValueError("filename must be a string")
    with _lock:
        if "filename" in kwargs:
            _state["filename"] = kwargs["filename"]
        for key in ("aggregate_stats", "profile_memory", "continuous_dump",
                    "xprof"):
            if key in kwargs:
                _state[key] = bool(kwargs[key])
        if "dump_period" in kwargs:
            _state["dump_period"] = kwargs["dump_period"]
        if "xprof_dir" in kwargs:
            _state["xprof_dir"] = kwargs["xprof_dir"]


def set_state(state="stop", profile_process="worker"):
    """Start/stop profiling (ref: python/mxnet/profiler.py:89). Starting also
    begins an xprof device trace when enabled (``xprof=True``) and a trace
    dir is configured or derivable — xprof start failures fall back to
    host-only tracing (e.g. when another trace is already active) — plus
    the memory-sampler / continuous-dump daemon threads when configured."""
    global _ACTIVE
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state == "run":
        with _lock:
            if _state["running"]:
                return
            _state["running"] = True
            _state["paused"] = False
            _ACTIVE = True
            _update_live()
            # xprof start/stop stays under _lock so a racing stop can
            # never observe a half-started device trace
            if _state["xprof"]:
                xdir = _state["xprof_dir"]
                if xdir is None:
                    xdir = os.path.join(
                        os.path.dirname(
                            os.path.abspath(_state["filename"])),
                        "xprof_trace")
                try:
                    import jax
                    jax.profiler.start_trace(xdir)
                    _state["xprof_active"] = True
                    _state["xprof_dir"] = xdir
                except Exception:
                    _state["xprof_active"] = False
            profile_memory = _state["profile_memory"]
            continuous = _state["continuous_dump"]
            period = _state["dump_period"]
        _start_daemons(profile_memory, continuous, period)
        # live export: MXNET_PROFILER_HTTP_PORT opts a run into the
        # /metrics endpoint without any code change; set_state('stop')
        # takes it down again (before the final trace dump — see the
        # shutdown-ordering note there)
        if _getenv("MXNET_PROFILER_HTTP_PORT"):
            try:
                serve_metrics()
            except (OSError, ValueError, OverflowError):
                pass  # port taken / malformed or out-of-range env value
                #      (bind raises OverflowError past 65535): host
                #      tracing must not die for a telemetry config typo
    else:
        with _lock:
            if not _state["running"]:
                return
            _state["running"] = False
            _ACTIVE = False
            _update_live()
            continuous = _state["continuous_dump"]
            if _state["xprof_active"]:
                _state["xprof_active"] = False
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        # shutdown ordering (ISSUE 8 satellite): the /metrics endpoint
        # goes down FIRST, before the daemons stop and the final trace
        # rewrite — a scrape racing shutdown could otherwise interleave
        # with a reset and observe a partially-reset histogram snapshot
        # (prometheus_text reads metrics() and _latency under two
        # separate lock acquisitions). Restart-able: the next
        # set_state('run') re-serves via the env autostart, and
        # serve_metrics() can be called again explicitly.
        stop_metrics_server()
        _stop_daemons()
        if continuous:
            _write_trace()  # final rewrite covers events since last period


def _start_daemons(profile_memory, continuous, period):
    """Background samplers for an active run. The trace file is written
    IMMEDIATELY when continuous dump is on (then every ``dump_period``), so
    it exists and parses from the first moment of the run.

    Runs outside set_state's lock hold (thread starts must not happen
    under _lock), so a racing set_state('stop') is handled two ways: a
    re-check of ``running`` under _lock before starting anything, and the
    loops themselves exiting once the run is over — a daemon that lost
    the race self-terminates within one period instead of leaking."""
    global _mem_thread, _dump_thread, _threads_stop
    with _lock:
        if not _state["running"]:
            return
        _threads_stop = threading.Event()
    stop = _threads_stop
    if profile_memory:
        sample_memory("start")
        sample_period = float(_getenv(
            "MXNET_PROFILER_MEMORY_SAMPLE_PERIOD", "0.1"))

        def _mem_loop():
            while not stop.wait(sample_period):
                if not _state["running"]:
                    return
                sample_memory("sampler")
                _sample_ledger()

        _mem_thread = threading.Thread(
            target=_mem_loop, daemon=True, name="profiler-mem-sampler")
        _mem_thread.start()
    if continuous:
        _write_trace()

        def _dump_loop():
            while not stop.wait(period):
                if not _state["running"]:
                    return
                try:
                    _write_trace()
                except Exception:
                    pass  # a failed rewrite must not kill the daemon

        _dump_thread = threading.Thread(
            target=_dump_loop, daemon=True, name="profiler-continuous-dump")
        _dump_thread.start()


def _stop_daemons():
    global _mem_thread, _dump_thread, _threads_stop
    if _threads_stop is not None:
        _threads_stop.set()
    for t in (_mem_thread, _dump_thread):
        if t is not None and t.is_alive():
            t.join(timeout=5)
    _mem_thread = _dump_thread = _threads_stop = None


def is_running():
    return _state["running"] and not _state["paused"]


def pause(profile_process="worker"):
    """ref: python/mxnet/profiler.py:193. Emits a ``profiler.pause``
    instant marker (while still active, so the trace explains its own
    gap) and then suspends recording."""
    global _ACTIVE
    with _lock:
        if _state["running"] and not _state["paused"]:
            _append_locked({"name": "profiler.pause", "cat": "profiler",
                            "ph": "i", "s": "g", "ts": _now_us(), "pid": PID,
                            "tid": LANES["user"]})
        _state["paused"] = True
        _ACTIVE = False
        _update_live()


def resume(profile_process="worker"):
    """ref: python/mxnet/profiler.py:209. Re-enables recording and emits a
    ``profiler.resume`` instant marker bounding the gap."""
    global _ACTIVE
    with _lock:
        was_paused = _state["paused"]
        _state["paused"] = False
        _ACTIVE = _state["running"]
        _update_live()
        if _state["running"] and was_paused:
            _append_locked({"name": "profiler.resume", "cat": "profiler",
                            "ph": "i", "s": "g", "ts": _now_us(), "pid": PID,
                            "tid": LANES["user"]})


def record_op(name, dur_us, category="operator", args=None,
              lane="imperative"):
    """Record one completed span into ``lane``. Always feeds the
    flight-recorder ring (the post-mortem black box, ISSUE 8); the
    trace event + aggregate row are recorded only while a profile run
    is active. Call sites guard with the shared ``_HOOKS and _LIVE``
    idiom. Mirrors the engine's ProfileOperator
    (src/engine/threaded_engine.h:83)."""
    if _flightrec.ENABLED:
        # inlined ring append (record_span's shape): the fused step
        # pays this once per step — the helper call + stats bump would
        # eat a third of the <0.1%-of-step flightrec budget
        _flightrec.RING.append(("X", name, category, LANES.get(lane, 7),
                                time.perf_counter(), dur_us, args))
    if not _ACTIVE:
        return
    end = _now_us()
    ev = {"name": name, "cat": category, "ph": "X",
          # mxlint: disable=MX014 (telemetry side channel: PID only tags the emitted event with the rank; no traced value depends on it)
          "ts": end - dur_us, "dur": dur_us, "pid": PID,
          "tid": LANES.get(lane, LANES["user"])}
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)
        st = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += dur_us
        st[2] = min(st[2], dur_us)
        st[3] = max(st[3], dur_us)


def record_counter(name, value, lane="user", series=None):
    """Emit a gauge sample (chrome Counter event) into ``lane`` — e.g. the
    io prefetch queue depth. ``series`` optionally names multiple stacked
    series (a dict of series -> value). Always feeds the flight-recorder
    ring; the trace event gates on the profile run."""
    if _flightrec.ENABLED:
        _flightrec.record_counter(name, series if series is not None
                                  else value, LANES.get(lane, 7))
    if not _ACTIVE:
        return
    args = dict(series) if series is not None else {"value": value}
    ev = {"name": name, "cat": "counter", "ph": "C", "ts": _now_us(),
          "pid": PID, "tid": LANES.get(lane, LANES["user"]), "args": args}
    with _lock:
        _append_locked(ev)


def account(name, delta, lane="kvstore", emit=True):
    """Accumulate a cumulative subsystem counter (kvstore bytes pushed,
    connect retries, heartbeats, io batches, ...) and, when a profile run
    is active, emit the running total as a Counter event so the trace
    shows it over time. The totals surface in ``dumps()`` and
    ``metrics()['counters']``.

    The total accumulates UNCONDITIONALLY — only the trace-event emission
    gates on ``_ACTIVE`` — so production counters (bytes moved, retries,
    worker deaths) never silently drop deltas while profiling is off.
    Accounting sites sit on network/IO/exception paths, not the per-op
    dispatch hot path, so the always-on cost is one lock + dict update
    per already-expensive event (plus one flight-recorder ring append —
    the black box keeps the counter timeline a post-mortem needs)."""
    with _lock:
        total = _counters.get(name, 0) + delta
        _counters[name] = total
        if emit and _ACTIVE:
            _append_locked({"name": name, "cat": "counter", "ph": "C",
                            # mxlint: disable=MX014 (telemetry side channel: rank tag on the emitted event only)
                            "ts": _now_us(), "pid": PID,
                            "tid": LANES.get(lane, LANES["user"]),
                            "args": {"value": total}})
    if emit and _flightrec.ENABLED:
        _flightrec.record_counter(name, total, LANES.get(lane, 7))


# -- latency histograms (ISSUE 6 tentpole c) ---------------------------------
# Log-spaced buckets: 8 sub-buckets per octave (power of 2), so every
# bucket spans <= 12.5% of its lower edge — percentile estimates carry a
# bounded ~6% relative error without storing raw samples. Bucket index
# packs (exponent, sub-bucket) from math.frexp; -1 is the [0, 0.5us)
# underflow bucket (sub-0.5us durations would otherwise pack to other
# negative indices that alias the sentinel's (0, 0) bounds — and emit
# duplicate le="0" series in one Prometheus exposition).
_LAT_SUBBITS = 3
_LAT_SUB = 1 << _LAT_SUBBITS


def _bucket_index(dur_us):
    if dur_us < 0.5:
        return -1
    m, e = math.frexp(dur_us)       # dur = m * 2**e, m in [0.5, 1)
    return (e << _LAT_SUBBITS) | int((m - 0.5) * 2 * _LAT_SUB)


def _bucket_bounds(idx):
    """(lo, hi) of bucket ``idx`` in microseconds."""
    if idx < 0:
        return 0.0, 0.5
    e, s = idx >> _LAT_SUBBITS, idx & (_LAT_SUB - 1)
    base = math.ldexp(1.0, e - 1)   # 2**(e-1)
    return base * (1.0 + s / _LAT_SUB), base * (1.0 + (s + 1) / _LAT_SUB)


def record_latency(name, dur_us):
    """Record one duration sample into the log-bucketed histogram
    ``name`` (the primitive behind ``metrics()['latency']`` and the
    Prometheus ``/metrics`` histograms). Hot-path callers guard with the
    inlined ``_HOOKS and _ACTIVE`` idiom (mxlint MX010); samples are only
    collected while a profile run is active."""
    if not _ACTIVE:
        return
    idx = _bucket_index(dur_us)
    with _lock:
        st = _latency.get(name)
        if st is None:
            st = _latency[name] = [0, 0.0, float("inf"), 0.0, {}]
        st[0] += 1
        st[1] += dur_us
        st[2] = min(st[2], dur_us)
        st[3] = max(st[3], dur_us)
        st[4][idx] = st[4].get(idx, 0) + 1


def _hist_percentile(buckets, count, q):
    """Quantile estimate by linear interpolation inside the bucket the
    cumulative count crosses ``q * count`` in."""
    target = q * count
    cum = 0.0
    for idx in sorted(buckets):
        n = buckets[idx]
        if cum + n >= target:
            lo, hi = _bucket_bounds(idx)
            return lo + (hi - lo) * ((target - cum) / n)
        cum += n
    return _bucket_bounds(max(buckets))[1]


def latency_metrics(reset=False):
    """{name: {count, sum_us, mean_us, min_us, max_us, p50_us, p95_us,
    p99_us}} — the ``metrics()['latency']`` section. ``reset`` clears
    the histograms under the SAME lock acquisition as the snapshot, so
    a sample recorded concurrently lands in either this snapshot or the
    next one — never in neither."""
    with _lock:
        snap = {n: (st[0], st[1], st[2], st[3], dict(st[4]))
                for n, st in _latency.items()}
        if reset:
            _latency.clear()
    out = {}
    for name, (count, total, mn, mx, buckets) in snap.items():
        if not count:
            continue
        out[name] = {
            "count": count,
            "sum_us": total,
            "mean_us": total / count,
            "min_us": mn,
            "max_us": mx,
            "p50_us": min(mx, _hist_percentile(buckets, count, 0.50)),
            "p95_us": min(mx, _hist_percentile(buckets, count, 0.95)),
            "p99_us": min(mx, _hist_percentile(buckets, count, 0.99)),
        }
    return out


def record_flow(name, flow_id, phase, ts_us=None, lane="kvstore",
                category="kvstore", args=None):
    """Emit one chrome-trace flow event (``ph:'s'`` start / ``'t'`` step /
    ``'f'`` finish) with the job-unique ``flow_id``. A flow binds to the
    enclosing duration span on its pid/tid at ``ts_us``, so a client RTT
    span and the server-side handling span render as one connected arrow
    in the merged trace (the cross-rank causality of ISSUE 6)."""
    if not _ACTIVE:
        return
    if phase not in ("s", "t", "f"):
        raise ValueError("flow phase must be 's', 't' or 'f', got %r"
                         % (phase,))
    ev = {"name": name, "cat": category, "ph": phase, "id": flow_id,
          "ts": _now_us() if ts_us is None else ts_us, "pid": PID,
          "tid": LANES.get(lane, LANES["user"])}
    if phase == "f":
        ev["bp"] = "e"  # bind to the enclosing slice, not the next one
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


# -- compile/device-time attribution (ISSUE 8 tentpole c) --------------------
# Every jit compile in the tree — the imperative dispatch cache, bulk
# segment runners, the fused train step — reports here: a span in the
# ``compile`` lane with its signature key, plus per-program
# cost-analysis numbers (flops / bytes accessed) and the comm_model's
# modeled compute/comm split when the compiler provided them. Like
# ``account``, the registry accumulates UNCONDITIONALLY (compiles are
# rare and expensive; their accounting must not depend on a profile
# run) — only the trace span gates on ``_ACTIVE``.
_compiles = {}  # name -> {count, total_us, key, flops, ...}


def record_compile(name, key=None, dur_us=0.0, flops=None,
                   bytes_accessed=None, comm_bytes=None,
                   modeled_compute_us=None, modeled_comm_us=None,
                   memory=None, args=None):
    """Record one jit compilation: ``name`` identifies the compiling
    subsystem + program (e.g. ``imperative:softmax``, ``fused_step``),
    ``key`` a short signature string (shape churn shows as the same
    name with a new key), ``dur_us`` the measured trace+compile(+first
    run) wall time. Optional attribution inputs: XLA cost-analysis
    ``flops``/``bytes_accessed``, collective payload ``comm_bytes``,
    and the comm_model's ``modeled_compute_us``/``modeled_comm_us`` —
    surfaced in ``metrics()['compile']`` and the ``dumps()``
    attribution table. ``memory`` (ISSUE 13b) is the program's
    ``compiled.memory_analysis()`` as a flat dict (``argument_bytes``,
    ``output_bytes``, ``temp_bytes``, ``generated_code_bytes``,
    ``peak_bytes``) — the modeled-peak half of the ``memory.headroom``
    gauge and the ``dumps()`` Memory table, keyed per signature via
    ``key`` like every other field here."""
    with _lock:
        st = _compiles.get(name)
        if st is None:
            st = _compiles[name] = {"count": 0, "total_us": 0.0,
                                    "last_us": 0.0, "key": None}
        st["count"] += 1
        st["total_us"] += float(dur_us)
        st["last_us"] = float(dur_us)
        if key is not None:
            st["key"] = str(key)
        for field, val in (("flops", flops),
                           ("bytes_accessed", bytes_accessed),
                           ("comm_bytes", comm_bytes),
                           ("modeled_compute_us", modeled_compute_us),
                           ("modeled_comm_us", modeled_comm_us)):
            if val is not None:
                st[field] = float(val)
        if memory is not None:
            st["memory"] = {k: int(v) for k, v in dict(memory).items()
                            if v is not None}
    # the modeled side of the roofline/MFU join (ISSUE 17): every
    # compile record feeds perfmodel keyed "name:key" — the same tag
    # the fused step threads through the watchdog beacon. Lazy import
    # (perfmodel bottom-imports this module); a perf-plane error must
    # never fail a compile.
    try:
        from ._debug import perfmodel as _perfmodel
        _perfmodel.note_compile(
            name, key, flops=flops, bytes_accessed=bytes_accessed,
            comm_bytes=comm_bytes, modeled_comm_us=modeled_comm_us,
            args=args)
    except Exception:
        pass
    ev_args = {"key": str(key)} if key is not None else {}
    if args:
        ev_args.update(args)
    record_op(name, dur_us, category="compile", args=ev_args or None,
              lane="compile")


def compile_stats():
    """Snapshot of the compile registry — ``metrics()['compile']``."""
    with _lock:
        return {n: dict(st) for n, st in _compiles.items()}


# -- compiled-program artifact capture (ISSUE 18, the hlolint feed) ----------
# The compile registry above keeps per-signature NUMBERS; hlolint needs
# the per-signature ARTIFACTS (HLO text + the contract metadata the
# builder knew at compile time: donated parameter numbers, replicated
# output slots, out-sharding specs, the analytic collective plan).
# Bounded ring of plain dicts — picklable, no executable references, so
# holding a record never pins device buffers. Re-lowerings of the same
# signature append (H005 compares collective order across them) rather
# than overwrite. Survives metrics(reset=True) like clock sync state:
# artifacts are analysis inputs, not accumulated telemetry.
_programs = []  # [{name, sig, hlo, meta, seq}, ...] oldest first
_PROGRAM_CAP = 32
_program_seq = 0  # monotonic capture counter — NEVER reset by the cap


def record_program(name, sig, hlo, meta=None):
    """Capture one compiled program for static analysis: ``name`` the
    compiling subsystem (``fused_step``), ``sig`` its signature tag
    (the ``fused_step:%08x`` roofline join key), ``hlo`` the
    ``compiled.as_text()`` dump, ``meta`` the contract dict hlolint
    rules check against (see tools/hlolint/capture.py for the keys).
    Each record carries a process-monotonic ``seq`` so consumers can
    select "captured after X" robustly — list indexes shift whenever
    the cap trims the front."""
    global _program_seq
    if not hlo:
        return
    rec = {"name": str(name), "sig": str(sig), "hlo": str(hlo),
           "meta": dict(meta) if meta else {}}
    with _lock:
        _program_seq += 1
        rec["seq"] = _program_seq
        _programs.append(rec)
        del _programs[:-_PROGRAM_CAP]


def program_records(name=None):
    """Captured program artifacts, oldest first — the hlolint feed."""
    with _lock:
        return [dict(r) for r in _programs
                if name is None or r["name"] == name]


def marker(name, args=None, lane="user", category="instant"):
    """Drop one instant event (chrome ``ph:"i"``) into ``lane`` at the
    current trace time — the public form of the internal ``_emit`` the
    faultpoint subsystem uses for ``fault:<point>`` markers. Always
    feeds the flight-recorder ring (markers are exactly the breadcrumbs
    a post-mortem needs); the trace event gates on the profile run, so
    call sites off the per-op hot path don't need their own guard."""
    if _flightrec.ENABLED:
        _flightrec.record_marker(name, category, LANES.get(lane, 7),
                                 args)
    if not _ACTIVE:
        return
    ev = {"name": name, "cat": category, "ph": "i", "s": "p",
          "ts": _now_us(), "pid": PID,
          "tid": LANES.get(lane, LANES["user"])}
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


# -- elastic-recovery accounting (ISSUE 7) -----------------------------------
# One shared store for the elastic-training event counters so BOTH sides
# of the recovery loop — the kvstore dead-node poll (kvstore_async.py)
# and the controller/checkpoint machinery (parallel/elastic.py) — count
# into the same ``metrics()['elastic']`` section without kvstore having
# to import the (heavy) parallel package.
_elastic = {}   # event name -> count (restores, reshards, preemptions, ...)


def bump_elastic(name, delta=1, args=None, lane="user"):
    """Count one elastic-recovery event into ``metrics()['elastic']``
    and, while a profile run is active, drop an ``elastic:<name>``
    instant marker next to the spans it perturbs. The count accumulates
    UNCONDITIONALLY (same contract as ``account``): recovery accounting
    must be trustworthy in production, not only under a profile run."""
    with _lock:
        _elastic[name] = _elastic.get(name, 0) + delta
    # marker() gates internally: flight-recorder ring always, trace
    # event only while a profile run is active
    marker("elastic:%s" % name, args=args, lane=lane,
           category="elastic")


def elastic_stats():
    """Snapshot of the elastic-recovery event counters — the
    ``metrics()['elastic']`` section (registered stats provider)."""
    with _lock:
        return dict(_elastic)


def reset_elastic_stats():
    with _lock:
        _elastic.clear()


def record_clock_sync(peer, offset_us, rtt_us, primary=False):
    """Record one clock-offset estimate against ``peer`` (an NTP-style
    sample from the kvstore heartbeat path: ``offset_us`` added to THIS
    process's trace clock gives the peer's). The minimum-RTT sample wins
    (tightest bound on the true offset). ``primary=True`` marks the
    canonical alignment target (PS server 0) that ``merge_traces``
    shifts this rank's shard by. Always recorded — calibration must not
    depend on when profiling was switched on."""
    with _lock:
        st = _clock_sync.get(peer)
        if st is None or rtt_us <= st["rtt_us"]:
            _clock_sync[peer] = st = {
                "offset_us": float(offset_us), "rtt_us": float(rtt_us),
                "samples": (st["samples"] if st else 0),
                "primary": bool(primary) or bool(st and st["primary"]),
            }
        st["samples"] += 1


def clock_sync():
    """Snapshot of the per-peer clock-offset estimates."""
    with _lock:
        return {p: dict(v) for p, v in _clock_sync.items()}


def sample_memory(trigger=None):
    """Sample per-device memory (``storage.stats()``) into Counter events
    on the ``memory`` lane and remember the snapshot for the ``dumps()``
    table / ``metrics()``. No-op unless profiling is active with
    ``profile_memory=True``. Called by the background sampler and at
    bulk-flush boundaries (the allocation-churn points)."""
    if not (_ACTIVE and _state["profile_memory"]):
        return
    try:
        from . import storage
        device_stats = storage.stats()
    except Exception:
        return
    ts = _now_us()
    events, snap = [], {}
    for s in device_stats:
        dev = str(s.device)
        events.append({
            "name": "memory:%s" % dev, "cat": "memory", "ph": "C",
            "ts": ts, "pid": PID, "tid": LANES["memory"],
            "args": {"bytes_in_use": s.bytes_in_use,
                     "peak_bytes_in_use": s.peak_bytes_in_use}})
        snap[dev] = {
            "bytes_in_use": s.bytes_in_use,
            "peak_bytes_in_use": s.peak_bytes_in_use,
            "peak_since_reset": getattr(s, "peak_since_reset", 0),
            "num_allocs": s.num_allocs,
        }
    with _lock:
        if not (_state["running"] and _state["profile_memory"]):
            return  # stopped while sampling: don't write into a dead run
        for ev in events:
            _append_locked(ev)
        _mem_last.update(snap)


def _sample_ledger():
    """Sampler-daemon companion to :func:`sample_memory` (ISSUE 13a):
    one stacked Counter series per allocation-ledger tag in the memory
    lane, plus the denser-cadence feed into the leak detector's rolling
    window. Runs ONLY on the daemon thread — the detector/dump chain
    must never be reachable from a bulk-flush/trace path (mxlint
    MX014's reachability contract)."""
    if not (_ACTIVE and _state["profile_memory"]):
        return
    try:
        from . import storage
        led = storage.ledger_metrics()
        by_tag = {t: b for t, b in led["by_tag"].items() if b}
        if by_tag:
            ev = {"name": "memory.ledger", "cat": "memory", "ph": "C",
                  "ts": _now_us(), "pid": PID, "tid": LANES["memory"],
                  "args": by_tag}
            with _lock:
                _append_locked(ev)
        from ._debug import memwatch as _memwatch
        _memwatch.observe(led)
    except Exception:
        pass  # ledger/detector trouble must not kill the sampler


def _lane_metadata():
    """chrome-trace metadata naming the process and every lane row.
    Rank 0 keeps the bare process name; other ranks qualify it so a
    merged multi-rank trace labels each process row."""
    pname = "mxnet_tpu" if PID == 0 else "mxnet_tpu rank %d" % PID
    events = [
        {"name": "process_name", "ph": "M", "pid": PID,
         "args": {"name": pname}},
        {"name": "process_sort_index", "ph": "M", "pid": PID,
         "args": {"sort_index": PID}},
    ]
    for lane, tid in sorted(LANES.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": PID,
                       "tid": tid, "args": {"name": lane}})
        events.append({"name": "thread_sort_index", "ph": "M", "pid": PID,
                       "tid": tid, "args": {"sort_index": tid}})
    return events


def _write_trace():
    """Atomically (write-temp + rename) dump the chrome trace, so a reader
    — or a crash — mid-rewrite never sees a truncated JSON file. Writers
    (continuous-dump daemon vs explicit dump()) are serialized under
    _dump_lock: they share the temp path, and an interleaved pair would
    publish corrupt JSON or race os.replace."""
    with _lock:
        data = {"traceEvents": _lane_metadata() + list(_events),
                "displayTimeUnit": "ms",
                # shard self-description for tools/trace_merge.py: which
                # rank this is and how its clock maps onto the peers'
                "metadata": {
                    "rank": PID,
                    "clock_sync": {p: dict(v)
                                   for p, v in _clock_sync.items()},
                }}
        fn = _state["filename"]
    with _dump_lock:
        _atomic_json_write(fn, data)


def _atomic_json_write(fn, data):
    """write-temp + rename under _dump_lock (caller holds it). Events may
    carry arbitrary user args (record_op/record_counter are public), so
    unserializable values degrade to str() instead of failing the dump;
    the temp file never outlives a failed write."""
    tmp = "%s.tmp.%d" % (fn, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, default=str)
        os.replace(tmp, fn)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def dump(finished=True, profile_process="worker", format="chrome"):
    """Write accumulated telemetry to ``filename``
    (ref: python/mxnet/profiler.py:122, DumpProfile profiler.h:299).

    ``format='chrome'`` (or ``'json'``): the chrome://tracing event file.
    ``format='metrics'``: the ``metrics()`` snapshot as JSON — the
    machine-readable aggregate surface for scrapers/bench harnesses."""
    if format in ("chrome", "json"):
        _write_trace()
    elif format == "metrics":
        data = metrics()
        with _lock:
            fn = _state["filename"]
        with _dump_lock:
            _atomic_json_write(fn, data)
    else:
        raise ValueError("format must be 'chrome', 'json' or 'metrics', "
                         "got %r" % (format,))


# Subsystem counter snapshots surfaced as named sections of metrics()
# and trailing lines of dumps() — the gluon fused train step registers
# "fused_step" here; other layers can follow the same pattern instead of
# growing bespoke metrics() fields.
_STATS_PROVIDERS = {}  # name -> (snapshot_fn, reset_fn or None)


def register_stats_provider(name, snapshot, reset=None):
    """Expose a subsystem's counter snapshot (a flat JSON-safe dict) as
    ``metrics()[name]`` and a line of ``dumps()``. ``snapshot()`` must be
    cheap and callable with profiling off; ``reset()`` (optional) is
    invoked by ``metrics(reset=True)`` / ``dumps(reset=True)``."""
    with _lock:
        _STATS_PROVIDERS[name] = (snapshot, reset)


# the elastic-recovery counters live in this module (see bump_elastic);
# registering them here makes metrics()['elastic'] exist from import
register_stats_provider("elastic", elastic_stats, reset_elastic_stats)


def _provider_sections(reset):
    """[(name, stats dict)] from the registered providers; a raising
    provider reports its error instead of killing the snapshot."""
    with _lock:
        providers = sorted(_STATS_PROVIDERS.items())
    out = []
    for name, (snapshot, reset_fn) in providers:
        try:
            stats = dict(snapshot())
            if reset and reset_fn is not None:
                reset_fn()
        except Exception as e:
            stats = {"error": "%s: %s" % (type(e).__name__, e)}
        out.append((name, stats))
    return out


def imperative_stats():
    """Imperative dispatch-cache counters (cache hits/misses/retraces/
    fallbacks and bulk-segment flushes/ops) — the observability surface of
    the MXNET_IMPERATIVE_JIT fast path. Always counted; zero when the fast
    path is disabled or unused."""
    from .ndarray import register as _register
    return _register.dispatch_stats()


def reset_imperative_stats():
    from .ndarray import register as _register
    _register.reset_dispatch_stats()


def _agg_rows():
    """[(name, count, total, min, max, avg)] snapshot — callers hold _lock."""
    return [(n, s[0], s[1], s[2] if s[0] else 0.0, s[3],
             s[1] / s[0] if s[0] else 0.0) for n, s in _agg.items()]


def metrics(reset=False):
    """One JSON-safe snapshot of everything the profiler knows: the
    aggregate span table, imperative dispatch-cache counters, cumulative
    subsystem counters (kvstore/io), and the last per-device memory sample.
    ``json.dumps(profiler.metrics())`` always works — bench.py and external
    scrapers consume this instead of parsing the ``dumps()`` text table."""
    with _lock:
        rows = _agg_rows()
        counters = dict(_counters)
        memory = {dev: dict(vals) for dev, vals in _mem_last.items()}
        compiles = {n: dict(st) for n, st in _compiles.items()}
        num_events = len(_events)
        if reset:
            _agg.clear()
            _events.clear()
            _counters.clear()
            _mem_last.clear()
            _compiles.clear()
    latency = latency_metrics(reset)
    # the memory section (ISSUE 13): the sampler's per-device snapshot
    # plus the storage-owned ledger/headroom/allocation counters —
    # composed OUTSIDE _lock (the ledger drain takes its own named
    # lock; nesting it under the event lock would order them)
    mem_section = {"devices": memory}
    try:
        from . import storage as _storage_mod
        mem_section.update(_storage_mod.memory_metrics())
    except Exception as e:
        mem_section["error"] = "%s: %s" % (type(e).__name__, e)
    # _clock_sync survives reset on purpose: it is calibration
    # state (clock offsets), not accumulated telemetry
    out = {
        "aggregate": {
            n: {"count": c, "total_us": tot, "min_us": mn, "max_us": mx,
                "avg_us": avg}
            for n, c, tot, mn, mx, avg in rows},
        "imperative": imperative_stats(),
        "counters": counters,
        "latency": latency,
        "memory": mem_section,
        "compile": compiles,
        "clock_sync": clock_sync(),
        "num_events": num_events,
    }
    for name, stats in _provider_sections(reset):
        out.setdefault(name, stats)
    if _locktrace.ENABLED:
        # runtime lock-order detector findings (MXNET_DEBUG_LOCKS=1):
        # acquisition-order inversions + locks held across jit/sync
        # boundaries, from mxnet_tpu._debug.locktrace
        out["locks"] = _locktrace.report()
    if reset:
        reset_imperative_stats()
    return out


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Return aggregate stats as a text table (ref: profiler.py:151,
    src/profiler/aggregate_stats.cc), followed by the imperative
    dispatch-cache counters, cumulative subsystem counters, and — when
    memory profiling sampled anything — a per-device memory table."""
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3,
               "avg": None}.get(sort_by, 1)
    with _lock:
        rows = _agg_rows()
        counters = dict(_counters)
        memory = {dev: dict(vals) for dev, vals in _mem_last.items()}
        compiles = {n: dict(st) for n, st in _compiles.items()}
        if reset:
            _agg.clear()
            _events.clear()
            _counters.clear()
            _mem_last.clear()
            _compiles.clear()
    latency = latency_metrics(reset)
    if key_idx is None:
        rows.sort(key=lambda r: r[5], reverse=not ascending)
    else:
        rows.sort(key=lambda r: r[key_idx + 1], reverse=not ascending)
    lines = ["%-40s %8s %12s %12s %12s %12s"
             % ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for n, c, tot, mn, mx, avg in rows:
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f"
                     % (n[:40], c, tot, mn, mx, avg))
    st = imperative_stats()
    lines.append("")
    lines.append("imperative dispatch: hits=%d misses=%d retraces=%d "
                 "fallbacks=%d bulk_flushes=%d bulk_ops=%d"
                 % (st["hits"], st["misses"], st["retraces"],
                    st["fallbacks"], st["bulk_flushes"], st["bulk_ops"]))
    for name, stats in _provider_sections(reset):
        lines.append("%s: %s" % (name, " ".join(
            "%s=%s" % (k, stats[k]) for k in sorted(stats))))
    if latency:
        lines.append("")
        lines.append("%-40s %8s %10s %10s %10s %10s" % (
            "Latency", "Count", "p50(us)", "p95(us)", "p99(us)",
            "Max(us)"))
        for name in sorted(latency):
            h = latency[name]
            lines.append("%-40s %8d %10.1f %10.1f %10.1f %10.1f" % (
                name[:40], h["count"], h["p50_us"], h["p95_us"],
                h["p99_us"], h["max_us"]))
    if compiles:
        lines.append("")
        lines.append("%-28s %6s %12s %14s %14s" % (
            "Compile", "Count", "Total(ms)", "GFLOPs", "GB moved"))
        for name in sorted(compiles):
            st = compiles[name]
            lines.append("%-28s %6d %12.1f %14s %14s" % (
                name[:28], st["count"], st["total_us"] / 1e3,
                "%.3f" % (st["flops"] / 1e9)
                if st.get("flops") is not None else "-",
                "%.4f" % (st["bytes_accessed"] / 1e9)
                if st.get("bytes_accessed") is not None else "-"))
        # attribution: modeled split of the measured step into compute
        # vs comm vs host time (ISSUE 8 tentpole c). Compute/comm are
        # the comm_model's projections from the program's cost analysis
        # (v5e assumptions, benchmark/comm_model.py ASSUMPTIONS); host
        # is the measured mean step minus both, i.e. everything the
        # device model cannot explain — dispatch, adoption, Python.
        attr_rows = []
        for name in sorted(compiles):
            st = compiles[name]
            if st.get("modeled_compute_us") is None:
                continue
            comp = st["modeled_compute_us"]
            comm = st.get("modeled_comm_us") or 0.0
            meas = latency.get("fused_step.step", {}).get("mean_us")
            host = max(0.0, meas - comp - comm) if meas else None
            attr_rows.append((name, comp, comm, meas, host))
        if attr_rows:
            lines.append("")
            lines.append("%-28s %12s %12s %12s %12s" % (
                "Attribution (modeled)", "compute(us)", "comm(us)",
                "step(us)", "host(us)"))
            for name, comp, comm, meas, host in attr_rows:
                lines.append("%-28s %12.1f %12.1f %12s %12s" % (
                    name[:28], comp, comm,
                    "%.1f" % meas if meas else "-",
                    "%.1f" % host if host is not None else "-"))
    # Memory table (ISSUE 13b): per-program modeled HBM footprint from
    # compiled.memory_analysis(), recorded by the fused-step AOT path
    mem_rows = [(n, st["memory"]) for n, st in sorted(compiles.items())
                if st.get("memory")]
    if mem_rows:
        lines.append("")
        lines.append("%-28s %10s %10s %10s %10s" % (
            "Memory (modeled)", "args(MB)", "out(MB)", "temp(MB)",
            "peak(MB)"))
        for name, mm in mem_rows:
            lines.append("%-28s %10.2f %10.2f %10.2f %10.2f" % (
                name[:28], mm.get("argument_bytes", 0) / 1e6,
                mm.get("output_bytes", 0) / 1e6,
                mm.get("temp_bytes", 0) / 1e6,
                mm.get("peak_bytes", 0) / 1e6))
    if counters:
        lines.append("counters: " + " ".join(
            "%s=%s" % (k, counters[k]) for k in sorted(counters)))
    # allocation ledger: live bytes by tag + headroom (storage owns it)
    try:
        from . import storage as _storage_mod
        smm = _storage_mod.memory_metrics()
    except Exception:
        smm = None
    if smm is not None:
        led = smm.get("ledger", {})
        by_tag = led.get("by_tag", {})
        if any(by_tag.values()):
            lines.append("")
            lines.append("memory ledger (live bytes): total=%d %s" % (
                led.get("total_bytes", 0),
                " ".join("%s=%d" % (t, by_tag[t])
                         for t in sorted(by_tag) if by_tag[t])))
        hr = smm.get("headroom")
        if hr:
            lines.append(
                "memory headroom: modeled_peak=%d device_peak=%d "
                "limit=%d%s" % (
                    hr.get("modeled_peak_bytes", 0),
                    hr.get("device_peak_bytes", 0),
                    hr.get("device_limit_bytes", 0),
                    " headroom=%d" % hr["headroom_bytes"]
                    if "headroom_bytes" in hr else ""))
        lines.append("memory accounting: alloc_fallbacks=%d "
                     "empty_cache_calls=%d" % (
                         smm.get("alloc_fallbacks", 0),
                         smm.get("empty_cache_calls", 0)))
    if memory:
        lines.append("")
        lines.append("%-24s %16s %16s %16s" % (
            "Device memory", "In use(B)", "Peak(B)", "PeakSinceReset(B)"))
        for dev in sorted(memory):
            m = memory[dev]
            lines.append("%-24s %16d %16d %16d" % (
                dev[:24], m["bytes_in_use"], m["peak_bytes_in_use"],
                m["peak_since_reset"]))
    # Goodput table (ISSUE 14): the run-level wall-clock partition —
    # live while a run is open, the last closed run's totals after.
    # Composed OUTSIDE _lock (goodput owns its own named lock).
    try:
        from ._debug import goodput as _goodput_mod
        g = _goodput_mod.snapshot()
    except Exception:
        g = None
    if g and g.get("run_id"):
        lines.append("")
        lines.append(
            "Goodput run=%s (%s): wall=%.3fs ratio=%.4f steps=%d "
            "warmup=%d replayed=%d recoveries=%d" % (
                g["run_id"], "open" if g.get("open") else
                g.get("outcome", "closed"), g.get("wall_s", 0.0),
                g.get("goodput_ratio", 0.0), g.get("steps", 0),
                g.get("warmup_steps", 0), g.get("replayed_steps", 0),
                g.get("recoveries", 0)))
        lines.append("%-16s %12s %8s" % ("Category", "Seconds",
                                         "Share"))
        wall = g.get("wall_s") or 0.0
        for c in _goodput_mod.CATEGORIES:
            s = g.get("%s_s" % c, 0.0)
            lines.append("%-16s %12.3f %7.1f%%" % (
                c, s, 100.0 * s / wall if wall > 0 else 0.0))
    # Roofline table (ISSUE 17): the modeled-vs-measured efficiency
    # join, per hot compile signature. Composed OUTSIDE _lock
    # (perfmodel owns its own named lock).
    try:
        from ._debug import perfmodel as _perfmodel_mod
        perf_rows = [r for r in _perfmodel_mod.table()
                     if r.get("median_s")]
    except Exception:
        perf_rows = []
    if perf_rows:
        lines.append("")
        lines.append("%-22s %6s %10s %6s %6s %8s %-9s %s" % (
            "Roofline", "Steps", "Med(us)", "MFU", "MemBW", "AI",
            "Bound", "comp/mem/comm/ovh(us)"))
        for r in perf_rows:
            t = r.get("terms_s") or {}
            lines.append(
                "%-22s %6d %10.1f %6s %6s %8s %-9s %s" % (
                    r["sig"][:22], r["steps"], r["median_s"] * 1e6,
                    "%.3f" % r["mfu"] if r["mfu"] is not None else "-",
                    "%.3f" % r["membw_util"]
                    if r["membw_util"] is not None else "-",
                    "%.1f" % r["intensity"]
                    if r["intensity"] is not None else "-",
                    r["bound"] or "-",
                    "/".join("%.1f" % (t.get(b, 0.0) * 1e6)
                             for b in _perfmodel_mod.BOUNDS)
                    if t else "-"))
    if reset:
        reset_imperative_stats()
    return "\n".join(lines)


# -- live export: Prometheus text + /metrics HTTP endpoint (ISSUE 6 d) ------

def _prom_num(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if math.isnan(v):
            return "NaN"
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text():
    """Render ``metrics()`` in the Prometheus text exposition format
    (version 0.0.4) — what the ``/metrics`` endpoint serves. Latency
    histograms become real Prometheus histograms (cumulative ``le``
    buckets in seconds plus ``_sum``/``_count``); cumulative subsystem
    counters become counters; memory, heartbeat ages and provider
    sections become gauges. Every sample carries a ``rank`` label so a
    job-wide scrape config can aggregate across workers."""
    m = metrics()
    rank = 'rank="%d"' % PID
    lines = []

    def emit(name, kind, help_text, samples):
        lines.append("# HELP %s %s" % (name, help_text))
        lines.append("# TYPE %s %s" % (name, kind))
        for labels, value in samples:
            lab = ",".join([rank] + labels)
            lines.append("%s{%s} %s" % (name, lab, _prom_num(value)))

    counter_samples = [
        (['name="%s"' % k], v) for k, v in sorted(m["counters"].items())]
    if counter_samples:
        emit("mxtpu_counter_total", "counter",
             "Cumulative subsystem counters (profiler.account).",
             counter_samples)
    # latency histograms: one family, name label distinguishes series
    with _lock:
        hists = {n: (st[0], st[1], dict(st[4]))
                 for n, st in _latency.items()}
    if hists:
        lines.append("# HELP mxtpu_latency_seconds Latency histograms "
                     "(profiler.record_latency), log-spaced buckets.")
        lines.append("# TYPE mxtpu_latency_seconds histogram")
        for name in sorted(hists):
            count, total, buckets = hists[name]
            series = '%s,name="%s"' % (rank, name)
            cum = 0
            for idx in sorted(buckets):
                cum += buckets[idx]
                le = _bucket_bounds(idx)[1] / 1e6  # us -> seconds
                lines.append(
                    'mxtpu_latency_seconds_bucket{%s,le="%.9g"} %d'
                    % (series, le, cum))
            lines.append('mxtpu_latency_seconds_bucket{%s,le="+Inf"} %d'
                         % (series, count))
            lines.append("mxtpu_latency_seconds_sum{%s} %s"
                         % (series, _prom_num(total / 1e6)))
            lines.append("mxtpu_latency_seconds_count{%s} %d"
                         % (series, count))
    mem = m["memory"]
    mem_samples = []
    for dev, vals in sorted(mem.get("devices", {}).items()):
        for k, v in sorted(vals.items()):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                mem_samples.append(
                    (['device="%s"' % dev, 'stat="%s"' % k], v))
    if mem_samples:
        emit("mxtpu_memory_bytes", "gauge",
             "Per-device memory stats (storage.stats).", mem_samples)
    led = mem.get("ledger", {})
    led_samples = [(['tag="%s"' % t], b)
                   for t, b in sorted(led.get("by_tag", {}).items())]
    if led_samples:
        emit("mxtpu_memory_ledger_bytes", "gauge",
             "Live device bytes by allocation-ledger tag "
             "(storage.ledger_metrics).", led_samples)
    alloc_samples = [
        (['name="%s"' % k], mem[k])
        for k in ("alloc_fallbacks", "empty_cache_calls") if k in mem]
    if alloc_samples:
        emit("mxtpu_memory_alloc_events_total", "counter",
             "Allocation-accounting counters (storage.counters).",
             alloc_samples)
    hr = mem.get("headroom")
    if hr:
        emit("mxtpu_memory_headroom_bytes", "gauge",
             "Modeled program peak vs measured peak vs device limit "
             "(storage.headroom).",
             [(['stat="%s"' % k], v) for k, v in sorted(hr.items())])
    # span aggregates: count + total time per named span
    agg_counts, agg_totals = [], []
    for name, st in sorted(m["aggregate"].items()):
        agg_counts.append((['name="%s"' % name], st["count"]))
        agg_totals.append((['name="%s"' % name], st["total_us"] / 1e6))
    if agg_counts:
        emit("mxtpu_span_count", "counter",
             "Completed span count per name (record_op).", agg_counts)
        emit("mxtpu_span_seconds_total", "counter",
             "Total span time per name (record_op).", agg_totals)
    # registered stats providers (fused_step, faults, kvstore_server,
    # imperative): flat numeric gauges
    sections = [("imperative", m.get("imperative", {}))]
    sections += [(k, v) for k, v in sorted(m.items())
                 if k not in ("aggregate", "imperative", "counters",
                              "latency", "memory", "clock_sync",
                              "num_events", "locks")
                 and isinstance(v, dict)]
    gauge_samples = []
    for section, stats in sections:
        for k, v in sorted(stats.items()):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauge_samples.append(
                    (['section="%s"' % section, 'name="%s"' % k], v))
    if gauge_samples:
        emit("mxtpu_stat", "gauge",
             "Subsystem stats providers (register_stats_provider).",
             gauge_samples)
    # run-level goodput partition (ISSUE 14): dedicated families beyond
    # the generic mxtpu_stat{section="goodput"} gauges, so dashboards
    # can stack the categories without label gymnastics
    g = m.get("goodput")
    if isinstance(g, dict) and g.get("run_id"):
        try:
            from ._debug import goodput as _goodput_mod
            cats = _goodput_mod.CATEGORIES
        except Exception:
            cats = ()
        cat_samples = [(['category="%s"' % c], g.get("%s_s" % c, 0.0))
                       for c in cats]
        if cat_samples:
            emit("mxtpu_goodput_seconds", "gauge",
                 "Run wall-clock by goodput category "
                 "(goodput.snapshot).", cat_samples)
        emit("mxtpu_goodput_ratio", "gauge",
             "Productive (compute) fraction of run wall-clock.",
             [([], g.get("goodput_ratio", 0.0))])
        emit("mxtpu_goodput_steps_total", "counter",
             "Completed representative steps in the run.",
             [(['kind="steps"'], g.get("steps", 0)),
              (['kind="warmup"'], g.get("warmup_steps", 0)),
              (['kind="replayed"'], g.get("replayed_steps", 0))])
    # roofline/MFU attribution (ISSUE 17): per-signature utilization
    # gauges beyond the flat mxtpu_stat{section="perf"} scalars, so a
    # dashboard can plot each hot program's MFU and binding term
    p = m.get("perf")
    per_sig = p.get("per_signature") if isinstance(p, dict) else None
    if per_sig:
        mfu_samples = [
            (['signature="%s"' % s], r["mfu"])
            for s, r in sorted(per_sig.items())
            if r.get("mfu") is not None]
        if mfu_samples:
            emit("mxtpu_mfu", "gauge",
                 "Model flop utilization per compile signature "
                 "(perfmodel: flops / (median step time x dtype "
                 "peak)).", mfu_samples)
        bw_samples = [
            (['signature="%s"' % s], r["membw_util"])
            for s, r in sorted(per_sig.items())
            if r.get("membw_util") is not None]
        if bw_samples:
            emit("mxtpu_membw_util", "gauge",
                 "HBM bandwidth utilization per compile signature "
                 "(perfmodel).", bw_samples)
        bound_samples = [
            (['signature="%s"' % s, 'bound="%s"' % r["bound"]], 1)
            for s, r in sorted(per_sig.items()) if r.get("bound")]
        if bound_samples:
            emit("mxtpu_roofline_bound", "gauge",
                 "Roofline verdict per signature: 1 on the binding "
                 "term (compute/memory/comm/overhead).", bound_samples)
    # training-health sentinels (ISSUE 15): dedicated families beyond
    # the generic mxtpu_stat{section="health"} gauges, so alerting
    # rules key on stable names
    h = m.get("health")
    if isinstance(h, dict) and h.get("enabled"):
        emit("mxtpu_health_steps_total", "counter",
             "Fused steps checked by the health sentinels, by outcome "
             "(healthmon).",
             [(['kind="checked"'], h.get("steps", 0)),
              (['kind="anomalous"'], h.get("anomalies", 0)),
              (['kind="nonfinite"'], h.get("nonfinite_steps", 0)),
              (['kind="loss_spike"'], h.get("loss_spikes", 0)),
              (['kind="skipped"'], h.get("skipped_steps", 0)),
              (['kind="amp_overflow_skip"'],
               h.get("amp_overflow_skips", 0))])
        emit("mxtpu_health_anomaly", "gauge",
             "1 while inside an anomaly episode (latched until a "
             "clean step).",
             [([], h.get("in_episode", 0))])
        emit("mxtpu_health_loss", "gauge",
             "Newest observed mean loss and its rolling median "
             "(the spike-envelope baseline).",
             [(['stat="last"'], h.get("last_loss", 0.0)),
              (['stat="median"'], h.get("loss_median", 0.0))])
    emit("mxtpu_profiler_events", "gauge",
         "Raw trace events currently buffered.",
         [([], m["num_events"])])
    return "\n".join(lines) + "\n"


_http_server = None
_http_thread = None


def serve_metrics(port=None, host="127.0.0.1"):
    """Start (idempotently) the zero-dependency ``/metrics`` HTTP
    endpoint rendering ``prometheus_text()`` — plus ``/metrics.json``
    with the raw ``metrics()`` dict — on ``host:port``. ``port=None``
    reads ``MXNET_PROFILER_HTTP_PORT``; ``0`` binds an ephemeral port.
    Returns the bound port. Binds loopback by default — expose it
    beyond the host via your scrape proxy, not by changing ``host``,
    unless the fabric is trusted. ``set_state('stop')`` shuts the
    endpoint down BEFORE the final trace dump (a scrape racing
    shutdown must not observe a partially-reset snapshot); call
    ``serve_metrics`` again to re-serve after a stop."""
    global _http_server, _http_thread
    with _lock:
        if _http_server is not None:
            return _http_server.server_address[1]
    if port is None:
        port = int(_getenv("MXNET_PROFILER_HTTP_PORT", "0"))
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path in ("/metrics", "/"):
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = json.dumps(metrics()).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass  # a scrape every 15s must not spam stderr

    import socketserver

    class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    srv = _Server((host, int(port)), _Handler)
    with _lock:
        if _http_server is not None:  # lost the race to another starter
            srv.server_close()
            return _http_server.server_address[1]
        _http_server = srv
    _http_thread = threading.Thread(target=srv.serve_forever,
                                    kwargs={"poll_interval": 0.2},
                                    daemon=True, name="profiler-metrics")
    _http_thread.start()
    return srv.server_address[1]


def stop_metrics_server():
    """Shut the ``/metrics`` endpoint down (no-op when not serving)."""
    global _http_server, _http_thread
    with _lock:
        srv, _http_server = _http_server, None
        thread, _http_thread = _http_thread, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if thread is not None:
        thread.join(timeout=5)


# -- multi-rank trace merge (ISSUE 6 tentpole b) -----------------------------

def merge_traces(shards, output=None, align=True):
    """Merge per-rank chrome-trace shards into one job-wide trace.

    ``shards``: paths to (or already-loaded dicts of) trace files dumped
    by each rank (each carries ``metadata.rank`` and the
    ``metadata.clock_sync`` offsets measured on the kvstore heartbeat
    path). Every event's ``pid`` is forced to its shard's rank and, when
    ``align`` (default), its timestamp is shifted by the shard's primary
    clock offset so all ranks share PS server 0's clock — the flow
    events stamped on the wire then pair up client→server in one
    timeline. Writes atomically to ``output`` when given.

    Returns ``(merged_dict, summary)`` where ``summary`` carries per-
    rank offsets and the flow-pairing tally (``flows_started``,
    ``flows_finished``, ``flows_paired``)."""
    loaded = []
    for i, sh in enumerate(shards):
        if isinstance(sh, str):
            with open(sh) as f:
                sh = json.load(f)
        loaded.append(sh)
    events = []
    summary = {"ranks": [], "offsets_us": {}, "events": 0,
               "flightrec_shards": 0}
    seen_meta = set()
    for i, sh in enumerate(loaded):
        meta = sh.get("metadata", {}) or {}
        rank = meta.get("rank")
        if rank is None:  # pre-ISSUE-6 shard: fall back to position
            rank = i
        # a flight-recorder post-mortem shard (ISSUE 8): same rank/pid
        # and timebase as the live profiler shards, but every event is
        # tagged so the merged view distinguishes black-box evidence
        # from live-profile evidence (they can overlap when profiling
        # was on at crash time)
        flightrec = bool(meta.get("flightrec"))
        summary["flightrec_shards"] += int(flightrec)
        offset = 0.0
        sync = meta.get("clock_sync", {}) or {}
        if align and sync:
            primaries = [v for v in sync.values() if v.get("primary")] \
                or list(sync.values())
            best = min(primaries, key=lambda v: v.get("rtt_us", 0.0))
            offset = float(best.get("offset_us", 0.0))
        summary["ranks"].append(rank)
        summary["offsets_us"][str(rank)] = offset
        for ev in sh.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = rank
            if ev.get("ph") == "M":
                # one metadata event per (pid, name, tid): shards
                # re-emit lane metadata on every dump
                key = (rank, ev.get("name"), ev.get("tid"))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                if ev.get("name") == "process_name" and rank != 0:
                    ev["args"] = {"name": "mxnet_tpu rank %d" % rank}
            if "ts" in ev:
                ev["ts"] = ev["ts"] + offset
            if flightrec and ev.get("ph") != "M":
                a = dict(ev.get("args", ()))
                a["source"] = "flightrec"
                ev["args"] = a
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", -1.0))
    starts = {e["id"] for e in events
              if e.get("ph") == "s" and "id" in e}
    finishes = {e["id"] for e in events
                if e.get("ph") == "f" and "id" in e}
    summary["flows_started"] = len(starts)
    summary["flows_finished"] = len(finishes)
    summary["flows_paired"] = len(starts & finishes)
    summary["events"] = len(events)
    merged = {"traceEvents": events, "displayTimeUnit": "ms",
              "metadata": {"merged_from": summary["ranks"],
                           "offsets_us": summary["offsets_us"]}}
    if output is not None:
        with _dump_lock:
            _atomic_json_write(output, merged)
    return merged, summary


def _reset():
    """Stop profiling and clear every recorded artifact (test helper)."""
    set_state("stop")
    stop_metrics_server()
    with _lock:
        _events.clear()
        _agg.clear()
        _counters.clear()
        _mem_last.clear()
        _latency.clear()
        _clock_sync.clear()
        _elastic.clear()
        _compiles.clear()
        del _programs[:]
    reset_imperative_stats()
    try:
        from . import storage as _storage_mod
        _storage_mod.ledger_reset()
    except Exception:
        pass
    try:
        from ._debug import perfmodel as _perfmodel_mod
        _perfmodel_mod.reset()
    except Exception:
        pass


def _emit(name, ph, cat, ts=None, args=None, tid=None):
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": _now_us() if ts is None else ts, "pid": PID,
          "tid": LANES["user"] if tid is None else tid}
    if args is not None:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


# -- user-defined profiling objects (ref: profiler.py:226-491) ---------------

class Domain:
    """Named grouping for profiling objects (ref: profiler.py:226)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _ph_cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is None:
            return
        if is_running():
            dur = _now_us() - self._start
            record_op("%s::%s" % (self.domain, self.name), dur,
                      category=self._ph_cat, lane="user")
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    def __str__(self):
        return self.name


class Task(_Span):
    """ref: profiler.py:285."""
    _ph_cat = "task"


class Frame(_Span):
    """ref: profiler.py:327."""
    _ph_cat = "frame"


class Event(_Span):
    """ref: profiler.py:369 (domain-less span)."""
    _ph_cat = "event"

    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    """Numeric counter emitted into the trace (ref: profiler.py:405)."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_running():
            _emit(self.name, "C", "counter",
                  args={str(self.domain): self._value})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self

    def __str__(self):
        return "%s=%s" % (self.name, self._value)


class Marker:
    """Instant event (ref: profiler.py:475)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if is_running():
            _emit(self.name, "i", "marker", args={"scope": scope})


# Fault-injection trigger counters (mxnet_tpu._debug.faultpoint): the
# chaos-testing accounting surface — every injected fault must be
# visible in metrics()['faults'] (tests/test_faultpoints.py asserts it).
# Registered here (not in faultpoint) because faultpoint loads as part
# of the _debug package import above, before this module finishes.
from ._debug import faultpoint as _faultpoint  # noqa: E402

register_stats_provider("faults", _faultpoint.metrics,
                        _faultpoint.reset_counters)

# Flight-recorder occupancy/dump accounting (ISSUE 8): always-on black
# box, so its health belongs in every metrics() snapshot.
register_stats_provider("flightrec", _flightrec.stats)

# Watchdog beacon stats: imported HERE (module bottom — the watchdog
# registers itself via register_stats_provider, which must already be
# defined) rather than from _debug/__init__, so every process has a
# metrics()['watchdog'] section even before the fused step or kvstore
# pull it in.
from ._debug import watchdog as _watchdog  # noqa: E402,F401


# deprecated aliases kept for parity (ref: profiler.py:70,109,143)
def profiler_set_config(mode="symbolic", filename="profile.json"):
    set_config(filename=filename)


def profiler_set_state(state="stop"):
    set_state(state)


def dump_profile():
    dump(True)
