"""Profiler: per-op tracing + user Domains/Tasks/Counters/Events.

TPU-native re-design of the reference profiler (ref: python/mxnet/profiler.py,
src/profiler/profiler.h:251, src/profiler/aggregate_stats.cc). The reference
hooks every engine OprBlock; here the analog is twofold:

* **Device-side**: when a profile run is active we start a ``jax.profiler``
  trace (xprof) so XLA:TPU emits per-HLO timing — the TPU equivalent of the
  engine's per-op ProfileOperator hooks.
* **Host-side**: an in-process event recorder mirrors the reference's
  chrome://tracing JSON dump (``DumpProfile``, profiler.h:299) and aggregate
  table (``dumps``, aggregate_stats.cc), and backs the user-facing
  Domain/Task/Frame/Event/Counter/Marker objects
  (ref: python/mxnet/profiler.py:226-491).

Scoped op timing is recorded by the NDArray/op layer via ``record_op`` when
profiling is on (zero cost when off).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "set_config", "set_state", "dump", "dumps", "pause", "resume",
    "Domain", "Task", "Frame", "Event", "Counter", "Marker",
    "record_op", "is_running", "imperative_stats", "reset_imperative_stats",
]

_lock = threading.Lock()
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "aggregate_stats": False,
    "profile_memory": False,
    "xprof_dir": None,
    "xprof_active": False,
}
_events = []          # chrome-trace event dicts
_agg = {}             # name -> [count, total_us, min_us, max_us]
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def set_config(**kwargs):
    """Configure the profiler (ref: python/mxnet/profiler.py:33
    MXSetProcessProfilerConfig). Accepted keys: ``filename``,
    ``profile_all/profile_symbolic/profile_imperative/profile_memory/
    profile_api`` (accepted for parity; host+device tracing is unified here),
    ``aggregate_stats``, ``continuous_dump``, ``dump_period``,
    ``profile_process``, and TPU-specific ``xprof_dir`` (directory for an
    xprof/XLA device trace; defaults next to ``filename``)."""
    with _lock:
        if "filename" in kwargs:
            _state["filename"] = kwargs["filename"]
        if "aggregate_stats" in kwargs:
            _state["aggregate_stats"] = bool(kwargs["aggregate_stats"])
        if "profile_memory" in kwargs:
            _state["profile_memory"] = bool(kwargs["profile_memory"])
        if "xprof_dir" in kwargs:
            _state["xprof_dir"] = kwargs["xprof_dir"]
        for k in kwargs:
            if k not in ("filename", "aggregate_stats", "profile_memory",
                         "xprof_dir", "profile_all", "profile_symbolic",
                         "profile_imperative", "profile_api",
                         "continuous_dump", "dump_period", "profile_process"):
                raise ValueError("unknown profiler config key %r" % (k,))


def set_state(state="stop", profile_process="worker"):
    """Start/stop profiling (ref: python/mxnet/profiler.py:89). Starting also
    begins an xprof device trace when a trace dir is configured or derivable;
    xprof start failures fall back to host-only tracing (e.g. when another
    trace is already active)."""
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    with _lock:
        if state == "run" and not _state["running"]:
            _state["running"] = True
            _state["paused"] = False
            xdir = _state["xprof_dir"]
            if xdir is None:
                xdir = os.path.join(
                    os.path.dirname(os.path.abspath(_state["filename"])),
                    "xprof_trace")
            try:
                import jax
                jax.profiler.start_trace(xdir)
                _state["xprof_active"] = True
                _state["xprof_dir"] = xdir
            except Exception:
                _state["xprof_active"] = False
        elif state == "stop" and _state["running"]:
            _state["running"] = False
            if _state["xprof_active"]:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                _state["xprof_active"] = False


def is_running():
    return _state["running"] and not _state["paused"]


def pause(profile_process="worker"):
    """ref: python/mxnet/profiler.py:193."""
    _state["paused"] = True


def resume(profile_process="worker"):
    """ref: python/mxnet/profiler.py:209."""
    _state["paused"] = False


def record_op(name, dur_us, category="operator", args=None):
    """Record one completed op (called by the runtime when profiling is on).
    Mirrors the engine's ProfileOperator (src/engine/threaded_engine.h:83)."""
    if not is_running():
        return
    end = _now_us()
    ev = {"name": name, "cat": category, "ph": "X",
          "ts": end - dur_us, "dur": dur_us, "pid": 0, "tid": 0}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        st = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += dur_us
        st[2] = min(st[2], dur_us)
        st[3] = max(st[3], dur_us)


def _emit(name, ph, cat, ts=None, args=None, tid=0):
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": _now_us() if ts is None else ts, "pid": 0, "tid": tid}
    if args is not None:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def dump(finished=True, profile_process="worker"):
    """Write accumulated events as chrome://tracing JSON to ``filename``
    (ref: python/mxnet/profiler.py:122, DumpProfile profiler.h:299)."""
    with _lock:
        data = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        fn = _state["filename"]
    with open(fn, "w") as f:
        json.dump(data, f)


def imperative_stats():
    """Imperative dispatch-cache counters (cache hits/misses/retraces/
    fallbacks and bulk-segment flushes/ops) — the observability surface of
    the MXNET_IMPERATIVE_JIT fast path. Always counted; zero when the fast
    path is disabled or unused."""
    from .ndarray import register as _register
    return _register.dispatch_stats()


def reset_imperative_stats():
    from .ndarray import register as _register
    _register.reset_dispatch_stats()


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Return aggregate stats as a text table (ref: profiler.py:151,
    src/profiler/aggregate_stats.cc), followed by the imperative
    dispatch-cache counters."""
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3,
               "avg": None}.get(sort_by, 1)
    with _lock:
        rows = [(n, s[0], s[1], s[2] if s[0] else 0.0, s[3],
                 s[1] / s[0] if s[0] else 0.0) for n, s in _agg.items()]
        if reset:
            _agg.clear()
            _events.clear()
    if key_idx is None:
        rows.sort(key=lambda r: r[5], reverse=not ascending)
    else:
        rows.sort(key=lambda r: r[key_idx + 1], reverse=not ascending)
    lines = ["%-40s %8s %12s %12s %12s %12s"
             % ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for n, c, tot, mn, mx, avg in rows:
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f"
                     % (n[:40], c, tot, mn, mx, avg))
    st = imperative_stats()
    lines.append("")
    lines.append("imperative dispatch: hits=%d misses=%d retraces=%d "
                 "fallbacks=%d bulk_flushes=%d bulk_ops=%d"
                 % (st["hits"], st["misses"], st["retraces"],
                    st["fallbacks"], st["bulk_flushes"], st["bulk_ops"]))
    if reset:
        reset_imperative_stats()
    return "\n".join(lines)


# -- user-defined profiling objects (ref: profiler.py:226-491) ---------------

class Domain:
    """Named grouping for profiling objects (ref: profiler.py:226)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _ph_cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is None:
            return
        if is_running():
            dur = _now_us() - self._start
            record_op("%s::%s" % (self.domain, self.name), dur,
                      category=self._ph_cat)
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    def __str__(self):
        return self.name


class Task(_Span):
    """ref: profiler.py:285."""
    _ph_cat = "task"


class Frame(_Span):
    """ref: profiler.py:327."""
    _ph_cat = "frame"


class Event(_Span):
    """ref: profiler.py:369 (domain-less span)."""
    _ph_cat = "event"

    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    """Numeric counter emitted into the trace (ref: profiler.py:405)."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_running():
            _emit(self.name, "C", "counter",
                  args={str(self.domain): self._value})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self

    def __str__(self):
        return "%s=%s" % (self.name, self._value)


class Marker:
    """Instant event (ref: profiler.py:475)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if is_running():
            _emit(self.name, "i", "marker", args={"scope": scope})


# deprecated aliases kept for parity (ref: profiler.py:70,109,143)
def profiler_set_config(mode="symbolic", filename="profile.json"):
    set_config(filename=filename)


def profiler_set_state(state="stop"):
    set_state(state)


def dump_profile():
    dump(True)
