"""Profiler: per-op tracing + user Domains/Tasks/Counters/Events.

TPU-native re-design of the reference profiler (ref: python/mxnet/profiler.py,
src/profiler/profiler.h:251, src/profiler/aggregate_stats.cc). The reference
hooks every engine OprBlock; here the analog is twofold:

* **Device-side**: when a profile run is active we start a ``jax.profiler``
  trace (xprof) so XLA:TPU emits per-HLO timing — the TPU equivalent of the
  engine's per-op ProfileOperator hooks.
* **Host-side**: an in-process event recorder mirrors the reference's
  chrome://tracing JSON dump (``DumpProfile``, profiler.h:299) and aggregate
  table (``dumps``, aggregate_stats.cc), and backs the user-facing
  Domain/Task/Frame/Event/Counter/Marker objects
  (ref: python/mxnet/profiler.py:226-491).

The host trace is organized into stable **lanes** (chrome-trace tid rows
named via ``thread_name`` metadata, ≙ the reference's per-device/per-thread
profiling domains, profiler.h:120 DeviceStats): ``imperative`` (op dispatch),
``bulk`` (segment flushes), ``kvstore`` (push/pull/init + wire counters),
``io`` (prefetch spans + queue depth), ``autograd`` (backward sweeps),
``memory`` (per-device HBM counters), ``gluon`` (Trainer.step), and ``user``
(Domain/Task/... objects). Subsystems emit through ``record_op`` /
``record_counter`` / ``account`` and guard on ``profiler._ACTIVE`` first, so
everything is zero-cost when profiling is off.

``profile_memory`` samples ``storage.stats()`` (PJRT per-device
bytes_in_use/peak) on a background thread plus at bulk-flush boundaries —
the analog of the reference pool counters feeding MemoryProfiler.
``continuous_dump``/``dump_period`` rewrite the trace file atomically every
period (ref: MXSetContinuousProfileDump) so long runs are inspectable
mid-flight. ``metrics()`` returns the whole surface as one JSON-safe dict.
"""
from __future__ import annotations

import json
import os
import threading
import time

from ._debug import locktrace as _locktrace

__all__ = [
    "set_config", "set_state", "dump", "dumps", "pause", "resume",
    "Domain", "Task", "Frame", "Event", "Counter", "Marker",
    "record_op", "record_counter", "account", "sample_memory", "metrics",
    "is_running", "imperative_stats", "reset_imperative_stats", "LANES",
    "register_stats_provider",
]

# Stable pid/tid lanes of the host trace. tid doubles as the sort index.
LANES = {
    "imperative": 0,
    "bulk": 1,
    "kvstore": 2,
    "io": 3,
    "autograd": 4,
    "memory": 5,
    "gluon": 6,
    "user": 7,
}

_lock = _locktrace.named_lock("profiler.events")
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "aggregate_stats": False,
    "profile_memory": False,
    "continuous_dump": False,
    "dump_period": 1.0,
    "xprof": True,
    "xprof_dir": None,
    "xprof_active": False,
}
# Fast-path guard mirrored from (running and not paused). Subsystem hooks
# read this module attribute before building any event dict — the
# profiling-off cost of the whole telemetry layer is this one truth test
# (BENCH_MODEL=profiler_overhead keeps it honest).
_ACTIVE = False

_events = []          # chrome-trace event dicts
_agg = {}             # name -> [count, total_us, min_us, max_us]
_counters = {}        # cumulative subsystem counters (kvstore/io bytes, ...)
_mem_last = {}        # str(device) -> last sampled memory dict
_t0 = time.perf_counter()

# Trace-event cap: a multi-hour run with the 10Hz memory sampler + per-op
# spans must not grow _events (and the continuous-dump serialization of
# it) without bound. Aggregate/counter totals keep counting past the cap;
# only raw timeline events are dropped, tallied in
# counters['profiler.dropped_events'].
_MAX_EVENTS = int(os.environ.get("MXNET_PROFILER_MAX_EVENTS", "1000000"))
# serializes trace-file writers (continuous-dump daemon vs explicit
# dump()): both write the same temp path, and interleaved writers would
# break the atomic-rewrite guarantee
_dump_lock = _locktrace.named_lock("profiler.dump")


def _append_locked(ev):
    """Append one trace event; caller holds _lock. Drops (and tallies)
    events past _MAX_EVENTS so unbounded runs stay bounded."""
    if len(_events) >= _MAX_EVENTS:
        # mxlint: disable=MX003 (caller holds _lock — the function's contract, see docstring)
        _counters["profiler.dropped_events"] = \
            _counters.get("profiler.dropped_events", 0) + 1
        return
    # mxlint: disable=MX003 (caller holds _lock — the function's contract, see docstring)
    _events.append(ev)


_mem_thread = None
_dump_thread = None
_threads_stop = None

_VALID_CONFIG_KEYS = frozenset((
    "filename", "aggregate_stats", "profile_memory", "continuous_dump",
    "dump_period", "xprof", "xprof_dir", "profile_all", "profile_symbolic",
    "profile_imperative", "profile_api", "profile_process",
))


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def set_config(**kwargs):
    """Configure the profiler (ref: python/mxnet/profiler.py:33
    MXSetProcessProfilerConfig). Accepted keys: ``filename``,
    ``profile_all/profile_symbolic/profile_imperative/profile_api``
    (accepted for parity; host+device tracing is unified here),
    ``profile_memory`` (background HBM sampling into the ``memory`` lane),
    ``aggregate_stats``, ``continuous_dump``/``dump_period`` (atomic
    periodic trace rewrite), ``profile_process``, and TPU-specific
    ``xprof`` (bool: start a device trace, default True) / ``xprof_dir``
    (directory for it; defaults next to ``filename``).

    The whole kwargs dict is validated before ANY of it is applied, so a
    bad call can never leave the config half-mutated."""
    if not set(kwargs) <= _VALID_CONFIG_KEYS:
        bad = sorted(set(kwargs) - _VALID_CONFIG_KEYS)
        raise ValueError("unknown profiler config key%s %s"
                         % ("s" if len(bad) > 1 else "", ", ".join(
                             repr(k) for k in bad)))
    if "dump_period" in kwargs:
        period = float(kwargs["dump_period"])
        if period <= 0:
            raise ValueError("dump_period must be > 0, got %r"
                             % (kwargs["dump_period"],))
        kwargs["dump_period"] = period
    if "filename" in kwargs and not isinstance(kwargs["filename"], str):
        raise ValueError("filename must be a string")
    with _lock:
        if "filename" in kwargs:
            _state["filename"] = kwargs["filename"]
        for key in ("aggregate_stats", "profile_memory", "continuous_dump",
                    "xprof"):
            if key in kwargs:
                _state[key] = bool(kwargs[key])
        if "dump_period" in kwargs:
            _state["dump_period"] = kwargs["dump_period"]
        if "xprof_dir" in kwargs:
            _state["xprof_dir"] = kwargs["xprof_dir"]


def set_state(state="stop", profile_process="worker"):
    """Start/stop profiling (ref: python/mxnet/profiler.py:89). Starting also
    begins an xprof device trace when enabled (``xprof=True``) and a trace
    dir is configured or derivable — xprof start failures fall back to
    host-only tracing (e.g. when another trace is already active) — plus
    the memory-sampler / continuous-dump daemon threads when configured."""
    global _ACTIVE
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state == "run":
        with _lock:
            if _state["running"]:
                return
            _state["running"] = True
            _state["paused"] = False
            _ACTIVE = True
            # xprof start/stop stays under _lock so a racing stop can
            # never observe a half-started device trace
            if _state["xprof"]:
                xdir = _state["xprof_dir"]
                if xdir is None:
                    xdir = os.path.join(
                        os.path.dirname(
                            os.path.abspath(_state["filename"])),
                        "xprof_trace")
                try:
                    import jax
                    jax.profiler.start_trace(xdir)
                    _state["xprof_active"] = True
                    _state["xprof_dir"] = xdir
                except Exception:
                    _state["xprof_active"] = False
            profile_memory = _state["profile_memory"]
            continuous = _state["continuous_dump"]
            period = _state["dump_period"]
        _start_daemons(profile_memory, continuous, period)
    else:
        with _lock:
            if not _state["running"]:
                return
            _state["running"] = False
            _ACTIVE = False
            continuous = _state["continuous_dump"]
            if _state["xprof_active"]:
                _state["xprof_active"] = False
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        _stop_daemons()
        if continuous:
            _write_trace()  # final rewrite covers events since last period


def _start_daemons(profile_memory, continuous, period):
    """Background samplers for an active run. The trace file is written
    IMMEDIATELY when continuous dump is on (then every ``dump_period``), so
    it exists and parses from the first moment of the run.

    Runs outside set_state's lock hold (thread starts must not happen
    under _lock), so a racing set_state('stop') is handled two ways: a
    re-check of ``running`` under _lock before starting anything, and the
    loops themselves exiting once the run is over — a daemon that lost
    the race self-terminates within one period instead of leaking."""
    global _mem_thread, _dump_thread, _threads_stop
    with _lock:
        if not _state["running"]:
            return
        _threads_stop = threading.Event()
    stop = _threads_stop
    if profile_memory:
        sample_memory("start")
        sample_period = float(os.environ.get(
            "MXNET_PROFILER_MEMORY_SAMPLE_PERIOD", "0.1"))

        def _mem_loop():
            while not stop.wait(sample_period):
                if not _state["running"]:
                    return
                sample_memory("sampler")

        _mem_thread = threading.Thread(
            target=_mem_loop, daemon=True, name="profiler-mem-sampler")
        _mem_thread.start()
    if continuous:
        _write_trace()

        def _dump_loop():
            while not stop.wait(period):
                if not _state["running"]:
                    return
                try:
                    _write_trace()
                except Exception:
                    pass  # a failed rewrite must not kill the daemon

        _dump_thread = threading.Thread(
            target=_dump_loop, daemon=True, name="profiler-continuous-dump")
        _dump_thread.start()


def _stop_daemons():
    global _mem_thread, _dump_thread, _threads_stop
    if _threads_stop is not None:
        _threads_stop.set()
    for t in (_mem_thread, _dump_thread):
        if t is not None and t.is_alive():
            t.join(timeout=5)
    _mem_thread = _dump_thread = _threads_stop = None


def is_running():
    return _state["running"] and not _state["paused"]


def pause(profile_process="worker"):
    """ref: python/mxnet/profiler.py:193. Emits a ``profiler.pause``
    instant marker (while still active, so the trace explains its own
    gap) and then suspends recording."""
    global _ACTIVE
    with _lock:
        if _state["running"] and not _state["paused"]:
            _append_locked({"name": "profiler.pause", "cat": "profiler",
                            "ph": "i", "s": "g", "ts": _now_us(), "pid": 0,
                            "tid": LANES["user"]})
        _state["paused"] = True
        _ACTIVE = False


def resume(profile_process="worker"):
    """ref: python/mxnet/profiler.py:209. Re-enables recording and emits a
    ``profiler.resume`` instant marker bounding the gap."""
    global _ACTIVE
    with _lock:
        was_paused = _state["paused"]
        _state["paused"] = False
        _ACTIVE = _state["running"]
        if _state["running"] and was_paused:
            _append_locked({"name": "profiler.resume", "cat": "profiler",
                            "ph": "i", "s": "g", "ts": _now_us(), "pid": 0,
                            "tid": LANES["user"]})


def record_op(name, dur_us, category="operator", args=None,
              lane="imperative"):
    """Record one completed span into ``lane`` (called by the runtime when
    profiling is on). Mirrors the engine's ProfileOperator
    (src/engine/threaded_engine.h:83)."""
    if not _ACTIVE:
        return
    end = _now_us()
    ev = {"name": name, "cat": category, "ph": "X",
          "ts": end - dur_us, "dur": dur_us, "pid": 0,
          "tid": LANES.get(lane, LANES["user"])}
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)
        st = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += dur_us
        st[2] = min(st[2], dur_us)
        st[3] = max(st[3], dur_us)


def record_counter(name, value, lane="user", series=None):
    """Emit a gauge sample (chrome Counter event) into ``lane`` — e.g. the
    io prefetch queue depth. ``series`` optionally names multiple stacked
    series (a dict of series -> value)."""
    if not _ACTIVE:
        return
    args = dict(series) if series is not None else {"value": value}
    ev = {"name": name, "cat": "counter", "ph": "C", "ts": _now_us(),
          "pid": 0, "tid": LANES.get(lane, LANES["user"]), "args": args}
    with _lock:
        _append_locked(ev)


def account(name, delta, lane="kvstore", emit=True):
    """Accumulate a cumulative subsystem counter (kvstore bytes pushed,
    connect retries, heartbeats, io batches, ...) and, by default, emit the
    running total as a Counter event so the trace shows it over time. The
    totals surface in ``dumps()`` and ``metrics()['counters']``."""
    if not _ACTIVE:
        return
    with _lock:
        total = _counters.get(name, 0) + delta
        _counters[name] = total
        if emit:
            _append_locked({"name": name, "cat": "counter", "ph": "C",
                            "ts": _now_us(), "pid": 0,
                            "tid": LANES.get(lane, LANES["user"]),
                            "args": {"value": total}})


def sample_memory(trigger=None):
    """Sample per-device memory (``storage.stats()``) into Counter events
    on the ``memory`` lane and remember the snapshot for the ``dumps()``
    table / ``metrics()``. No-op unless profiling is active with
    ``profile_memory=True``. Called by the background sampler and at
    bulk-flush boundaries (the allocation-churn points)."""
    if not (_ACTIVE and _state["profile_memory"]):
        return
    try:
        from . import storage
        device_stats = storage.stats()
    except Exception:
        return
    ts = _now_us()
    events, snap = [], {}
    for s in device_stats:
        dev = str(s.device)
        events.append({
            "name": "memory:%s" % dev, "cat": "memory", "ph": "C",
            "ts": ts, "pid": 0, "tid": LANES["memory"],
            "args": {"bytes_in_use": s.bytes_in_use,
                     "peak_bytes_in_use": s.peak_bytes_in_use}})
        snap[dev] = {
            "bytes_in_use": s.bytes_in_use,
            "peak_bytes_in_use": s.peak_bytes_in_use,
            "peak_since_reset": getattr(s, "peak_since_reset", 0),
            "num_allocs": s.num_allocs,
        }
    with _lock:
        if not (_state["running"] and _state["profile_memory"]):
            return  # stopped while sampling: don't write into a dead run
        for ev in events:
            _append_locked(ev)
        _mem_last.update(snap)


def _lane_metadata():
    """chrome-trace metadata naming the process and every lane row."""
    events = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "mxnet_tpu"}},
        {"name": "process_sort_index", "ph": "M", "pid": 0,
         "args": {"sort_index": 0}},
    ]
    for lane, tid in sorted(LANES.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": 0,
                       "tid": tid, "args": {"name": lane}})
        events.append({"name": "thread_sort_index", "ph": "M", "pid": 0,
                       "tid": tid, "args": {"sort_index": tid}})
    return events


def _write_trace():
    """Atomically (write-temp + rename) dump the chrome trace, so a reader
    — or a crash — mid-rewrite never sees a truncated JSON file. Writers
    (continuous-dump daemon vs explicit dump()) are serialized under
    _dump_lock: they share the temp path, and an interleaved pair would
    publish corrupt JSON or race os.replace."""
    with _lock:
        data = {"traceEvents": _lane_metadata() + list(_events),
                "displayTimeUnit": "ms"}
        fn = _state["filename"]
    with _dump_lock:
        _atomic_json_write(fn, data)


def _atomic_json_write(fn, data):
    """write-temp + rename under _dump_lock (caller holds it). Events may
    carry arbitrary user args (record_op/record_counter are public), so
    unserializable values degrade to str() instead of failing the dump;
    the temp file never outlives a failed write."""
    tmp = "%s.tmp.%d" % (fn, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, default=str)
        os.replace(tmp, fn)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def dump(finished=True, profile_process="worker", format="chrome"):
    """Write accumulated telemetry to ``filename``
    (ref: python/mxnet/profiler.py:122, DumpProfile profiler.h:299).

    ``format='chrome'`` (or ``'json'``): the chrome://tracing event file.
    ``format='metrics'``: the ``metrics()`` snapshot as JSON — the
    machine-readable aggregate surface for scrapers/bench harnesses."""
    if format in ("chrome", "json"):
        _write_trace()
    elif format == "metrics":
        data = metrics()
        with _lock:
            fn = _state["filename"]
        with _dump_lock:
            _atomic_json_write(fn, data)
    else:
        raise ValueError("format must be 'chrome', 'json' or 'metrics', "
                         "got %r" % (format,))


# Subsystem counter snapshots surfaced as named sections of metrics()
# and trailing lines of dumps() — the gluon fused train step registers
# "fused_step" here; other layers can follow the same pattern instead of
# growing bespoke metrics() fields.
_STATS_PROVIDERS = {}  # name -> (snapshot_fn, reset_fn or None)


def register_stats_provider(name, snapshot, reset=None):
    """Expose a subsystem's counter snapshot (a flat JSON-safe dict) as
    ``metrics()[name]`` and a line of ``dumps()``. ``snapshot()`` must be
    cheap and callable with profiling off; ``reset()`` (optional) is
    invoked by ``metrics(reset=True)`` / ``dumps(reset=True)``."""
    with _lock:
        _STATS_PROVIDERS[name] = (snapshot, reset)


def _provider_sections(reset):
    """[(name, stats dict)] from the registered providers; a raising
    provider reports its error instead of killing the snapshot."""
    with _lock:
        providers = sorted(_STATS_PROVIDERS.items())
    out = []
    for name, (snapshot, reset_fn) in providers:
        try:
            stats = dict(snapshot())
            if reset and reset_fn is not None:
                reset_fn()
        except Exception as e:
            stats = {"error": "%s: %s" % (type(e).__name__, e)}
        out.append((name, stats))
    return out


def imperative_stats():
    """Imperative dispatch-cache counters (cache hits/misses/retraces/
    fallbacks and bulk-segment flushes/ops) — the observability surface of
    the MXNET_IMPERATIVE_JIT fast path. Always counted; zero when the fast
    path is disabled or unused."""
    from .ndarray import register as _register
    return _register.dispatch_stats()


def reset_imperative_stats():
    from .ndarray import register as _register
    _register.reset_dispatch_stats()


def _agg_rows():
    """[(name, count, total, min, max, avg)] snapshot — callers hold _lock."""
    return [(n, s[0], s[1], s[2] if s[0] else 0.0, s[3],
             s[1] / s[0] if s[0] else 0.0) for n, s in _agg.items()]


def metrics(reset=False):
    """One JSON-safe snapshot of everything the profiler knows: the
    aggregate span table, imperative dispatch-cache counters, cumulative
    subsystem counters (kvstore/io), and the last per-device memory sample.
    ``json.dumps(profiler.metrics())`` always works — bench.py and external
    scrapers consume this instead of parsing the ``dumps()`` text table."""
    with _lock:
        rows = _agg_rows()
        counters = dict(_counters)
        memory = {dev: dict(vals) for dev, vals in _mem_last.items()}
        num_events = len(_events)
        if reset:
            _agg.clear()
            _events.clear()
            _counters.clear()
            _mem_last.clear()
    out = {
        "aggregate": {
            n: {"count": c, "total_us": tot, "min_us": mn, "max_us": mx,
                "avg_us": avg}
            for n, c, tot, mn, mx, avg in rows},
        "imperative": imperative_stats(),
        "counters": counters,
        "memory": memory,
        "num_events": num_events,
    }
    for name, stats in _provider_sections(reset):
        out.setdefault(name, stats)
    if _locktrace.ENABLED:
        # runtime lock-order detector findings (MXNET_DEBUG_LOCKS=1):
        # acquisition-order inversions + locks held across jit/sync
        # boundaries, from mxnet_tpu._debug.locktrace
        out["locks"] = _locktrace.report()
    if reset:
        reset_imperative_stats()
    return out


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Return aggregate stats as a text table (ref: profiler.py:151,
    src/profiler/aggregate_stats.cc), followed by the imperative
    dispatch-cache counters, cumulative subsystem counters, and — when
    memory profiling sampled anything — a per-device memory table."""
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3,
               "avg": None}.get(sort_by, 1)
    with _lock:
        rows = _agg_rows()
        counters = dict(_counters)
        memory = {dev: dict(vals) for dev, vals in _mem_last.items()}
        if reset:
            _agg.clear()
            _events.clear()
            _counters.clear()
            _mem_last.clear()
    if key_idx is None:
        rows.sort(key=lambda r: r[5], reverse=not ascending)
    else:
        rows.sort(key=lambda r: r[key_idx + 1], reverse=not ascending)
    lines = ["%-40s %8s %12s %12s %12s %12s"
             % ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for n, c, tot, mn, mx, avg in rows:
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f"
                     % (n[:40], c, tot, mn, mx, avg))
    st = imperative_stats()
    lines.append("")
    lines.append("imperative dispatch: hits=%d misses=%d retraces=%d "
                 "fallbacks=%d bulk_flushes=%d bulk_ops=%d"
                 % (st["hits"], st["misses"], st["retraces"],
                    st["fallbacks"], st["bulk_flushes"], st["bulk_ops"]))
    for name, stats in _provider_sections(reset):
        lines.append("%s: %s" % (name, " ".join(
            "%s=%s" % (k, stats[k]) for k in sorted(stats))))
    if counters:
        lines.append("counters: " + " ".join(
            "%s=%s" % (k, counters[k]) for k in sorted(counters)))
    if memory:
        lines.append("")
        lines.append("%-24s %16s %16s %16s" % (
            "Device memory", "In use(B)", "Peak(B)", "PeakSinceReset(B)"))
        for dev in sorted(memory):
            m = memory[dev]
            lines.append("%-24s %16d %16d %16d" % (
                dev[:24], m["bytes_in_use"], m["peak_bytes_in_use"],
                m["peak_since_reset"]))
    if reset:
        reset_imperative_stats()
    return "\n".join(lines)


def _reset():
    """Stop profiling and clear every recorded artifact (test helper)."""
    set_state("stop")
    with _lock:
        _events.clear()
        _agg.clear()
        _counters.clear()
        _mem_last.clear()
    reset_imperative_stats()


def _emit(name, ph, cat, ts=None, args=None, tid=None):
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": _now_us() if ts is None else ts, "pid": 0,
          "tid": LANES["user"] if tid is None else tid}
    if args is not None:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


# -- user-defined profiling objects (ref: profiler.py:226-491) ---------------

class Domain:
    """Named grouping for profiling objects (ref: profiler.py:226)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _ph_cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is None:
            return
        if is_running():
            dur = _now_us() - self._start
            record_op("%s::%s" % (self.domain, self.name), dur,
                      category=self._ph_cat, lane="user")
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    def __str__(self):
        return self.name


class Task(_Span):
    """ref: profiler.py:285."""
    _ph_cat = "task"


class Frame(_Span):
    """ref: profiler.py:327."""
    _ph_cat = "frame"


class Event(_Span):
    """ref: profiler.py:369 (domain-less span)."""
    _ph_cat = "event"

    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    """Numeric counter emitted into the trace (ref: profiler.py:405)."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_running():
            _emit(self.name, "C", "counter",
                  args={str(self.domain): self._value})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self

    def __str__(self):
        return "%s=%s" % (self.name, self._value)


class Marker:
    """Instant event (ref: profiler.py:475)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if is_running():
            _emit(self.name, "i", "marker", args={"scope": scope})


# Fault-injection trigger counters (mxnet_tpu._debug.faultpoint): the
# chaos-testing accounting surface — every injected fault must be
# visible in metrics()['faults'] (tests/test_faultpoints.py asserts it).
# Registered here (not in faultpoint) because faultpoint loads as part
# of the _debug package import above, before this module finishes.
from ._debug import faultpoint as _faultpoint  # noqa: E402

register_stats_provider("faults", _faultpoint.metrics,
                        _faultpoint.reset_counters)


# deprecated aliases kept for parity (ref: profiler.py:70,109,143)
def profiler_set_config(mode="symbolic", filename="profile.json"):
    set_config(filename=filename)


def profiler_set_state(state="stop"):
    set_state(state)


def dump_profile():
    dump(True)
