"""Custom operators in Python — mx.operator.CustomOp / CustomOpProp.

ref: python/mxnet/operator.py (CustomOp :378, CustomOpProp :512,
register :636) over src/operator/custom/custom-inl.h:52 CustomOperator
(the reference runs custom-op Python callbacks on a dedicated worker
thread pool inside the engine).

TPU-native redesign: the eager path runs the Python callbacks inline and
records a tape node whose vjp calls ``backward()`` — same recording
contract as every generated op. Inside a COMPILED graph (symbolic
executor / hybridize), a Custom node lowers to ``jax.pure_callback``: XLA
calls back onto the host for exactly this node, which is the TPU analog of
the reference's engine-thread escape hatch (everything around it stays
fused on device).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from . import autograd
from .context import current_context
from .ndarray import NDArray

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_CUSTOM_PROPS = {}  # mxlint: disable=MX003 (custom-op registration happens at model-setup time before threads dispatch ops)


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under ``op_type``
    (ref: python/mxnet/operator.py:636 register)."""
    def do_register(prop_cls):
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_prop(op_type):
    try:
        return _CUSTOM_PROPS[op_type]
    except KeyError:
        raise KeyError("custom op %r is not registered; call "
                       "mx.operator.register(%r) on a CustomOpProp "
                       "subclass first" % (op_type, op_type))


class CustomOp:
    """Base class for user-defined operators
    (ref: python/mxnet/operator.py:378)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """ref: operator.py CustomOp.assign."""
        if req in ("null",):
            return
        if req in ("write", "inplace"):
            dst._data = src._data.astype(dst._data.dtype) \
                if isinstance(src, NDArray) else jnp.asarray(
                    src, dst._data.dtype)
        elif req == "add":
            s = src._data if isinstance(src, NDArray) else jnp.asarray(src)
            dst._data = dst._data + s.astype(dst._data.dtype)
        else:
            raise ValueError("unknown req %r" % req)


class CustomOpProp:
    """Operator properties: arguments/outputs/shapes/types + factory
    (ref: python/mxnet/operator.py:512)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def infer_storage_type(self, in_stype):
        return (in_stype, ["default"] * len(self.list_outputs()),
                ["default"] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def _invoke_custom(op_type, inputs, kwargs):
    """Eager execution of a custom op; returns list of output NDArrays and
    enough context to register the tape node."""
    prop_cls = get_prop(op_type)
    prop = prop_cls(**kwargs)
    in_data = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
               for a in inputs]
    in_shapes = [list(a.shape) for a in in_data]
    shapes = prop.infer_shape(in_shapes)
    _, out_shapes, aux_shapes = shapes
    in_types = [a.dtype for a in in_data]
    _, out_types, aux_types = prop.infer_type(in_types)
    op = prop.create_operator(current_context(), in_shapes, in_types)
    out_data = [NDArray(jnp.zeros(tuple(s), dt))
                for s, dt in zip(out_shapes, out_types)]
    aux = [NDArray(jnp.zeros(tuple(s), dt))
           for s, dt in zip(aux_shapes, aux_types)]
    op.forward(is_train=autograd.is_training() or autograd.is_recording(),
               req=["write"] * len(out_data), in_data=in_data,
               out_data=out_data, aux=aux)

    if autograd.is_recording():
        n_in = len(in_data)

        def vjp_fn(cts):
            cts = cts if isinstance(cts, tuple) else (cts,)
            out_grad = [NDArray(jnp.asarray(c)) for c in cts]
            in_grad = [NDArray(jnp.zeros(a.shape, a.dtype))
                       for a in in_data]
            op.backward(req=["write"] * n_in, out_grad=out_grad,
                        in_data=in_data, out_data=out_data,
                        in_grad=in_grad, aux=aux)
            return tuple(g._data for g in in_grad)

        autograd.record_op("Custom:%s" % op_type, out_data, in_data,
                           vjp_fn)
    return out_data


def invoke(*inputs, op_type, **kwargs):
    """nd-level entry (``mx.nd.Custom``), ref: operator.py:
    ndarray custom invoke via MXCustomOp registry."""
    outs = _invoke_custom(op_type, list(inputs), kwargs)
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---------------------------------------------------------------------------
# compiled-graph lowering: Custom as a host callback island inside XLA
# ---------------------------------------------------------------------------

def _register_custom_graph_op():
    from .ops.registry import register as _reg_op

    @_reg_op("Custom")
    def Custom(*inputs, op_type=None, **kwargs):
        """Host-callback custom op inside a compiled graph
        (ref: src/operator/custom/custom-inl.h CustomOperator — the
        engine-thread version of the same escape hatch)."""
        if op_type is None:
            raise ValueError("Custom requires op_type")
        kwargs.pop("_training", None)
        prop = get_prop(op_type)(**kwargs)
        in_shapes = [list(x.shape) for x in inputs]
        _, out_shapes, _ = prop.infer_shape(in_shapes)
        in_types = [x.dtype for x in inputs]
        _, out_types, _ = prop.infer_type(in_types)
        results = tuple(jax.ShapeDtypeStruct(tuple(s), dt)
                        for s, dt in zip(out_shapes, out_types))

        def host_fwd(*arrays):
            prev = autograd.set_recording(False)
            try:
                outs = _invoke_custom(
                    op_type, [NDArray(jnp.asarray(_np.asarray(a)))
                              for a in arrays], kwargs)
            finally:
                autograd.set_recording(prev)
            return tuple(_np.asarray(o.asnumpy()) for o in outs)

        @jax.custom_vjp
        def core(*ins):
            out = jax.pure_callback(host_fwd, results, *ins)
            return out if len(results) > 1 else (out
                                                if isinstance(out, tuple)
                                                else (out,))

        def core_fwd(*ins):
            out = core(*ins)
            return out, ins

        def core_bwd(ins, cts):
            grad_results = tuple(
                jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                for x in ins)

            def host_bwd(*arrays):
                n = len(ins)
                in_arrays = arrays[:n]
                ct_arrays = arrays[n:]
                prop2 = get_prop(op_type)(**kwargs)
                in_nd = [NDArray(jnp.asarray(_np.asarray(a)))
                         for a in in_arrays]
                ishapes = [list(a.shape) for a in in_nd]
                _, oshapes, ashapes = prop2.infer_shape(ishapes)
                itypes = [a.dtype for a in in_nd]
                _, otypes, atypes = prop2.infer_type(itypes)
                op = prop2.create_operator(current_context(), ishapes,
                                           itypes)
                out_nd = [NDArray(jnp.zeros(tuple(s), dt))
                          for s, dt in zip(oshapes, otypes)]
                aux = [NDArray(jnp.zeros(tuple(s), dt))
                       for s, dt in zip(ashapes, atypes)]
                op.forward(is_train=True, req=["write"] * len(out_nd),
                           in_data=in_nd, out_data=out_nd, aux=aux)
                in_grad = [NDArray(jnp.zeros(a.shape, a.dtype))
                           for a in in_nd]
                op.backward(req=["write"] * len(in_nd),
                            out_grad=[NDArray(jnp.asarray(_np.asarray(c)))
                                      for c in ct_arrays],
                            in_data=in_nd, out_data=out_nd,
                            in_grad=in_grad, aux=aux)
                return tuple(_np.asarray(g.asnumpy()) for g in in_grad)

            cts = cts if isinstance(cts, tuple) else (cts,)
            return jax.pure_callback(host_bwd, grad_results,
                                     *(tuple(ins) + tuple(cts)))

        core.defvjp(core_fwd, core_bwd)
        out = core(*inputs)
        return out if len(results) > 1 else out[0]


_register_custom_graph_op()
