"""mx.np — NumPy-semantics array API.

TPU-native analog of the reference's NumPy-compatible frontend
(ref: python/mxnet/numpy/multiarray.py, 243 defs; backed by
src/operator/numpy/). The reference re-implements NumPy semantics as a
separate C++ op namespace (`_np_*` ops) because its legacy ops have MXNet
semantics (no zero-dim arrays, no true broadcasting on some ops). Here the
compute path is jax.numpy — already NumPy-semantics end to end — so each
function is a thin autograd-recording wrapper over the corresponding jnp
function, and ``ndarray`` is a subclass of the framework NDArray whose
operators follow NumPy type promotion.

Functions participate in ``autograd.record()`` exactly like registry ops:
the jax.vjp closure of the traced call is captured on the tape
(ref: src/imperative/imperative.cc:193 RecordOp analog).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as _onp

from .. import autograd
from ..base import canonical_dtype
from ..context import current_context
from ..ndarray.ndarray import NDArray, _is_tracer, _place

__all__ = ["ndarray", "array", "zeros", "ones", "full", "empty", "arange",
           "linspace", "logspace", "eye", "identity", "empty_like",
           "zeros_like", "ones_like", "full_like", "copy", "asarray",
           "hanning", "hamming", "blackman",
           "pi", "e", "inf", "nan", "newaxis", "euler_gamma",
           "float16", "float32", "float64", "int8", "int16", "int32",
           "int64", "uint8", "bool_", "bfloat16"]

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma

# dtype objects re-exported like the reference (mx.np.float32 is np.float32)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
bfloat16 = jnp.bfloat16


from ..base import is_inexact_dtype as _is_inexact  # noqa: E402


def _wrap_out(x):
    if isinstance(x, NDArray):
        return x
    return ndarray(x)


def _np_invoke(fn, args, kwargs, op_name=None):
    """Run a jnp function over NDArray/scalar args with autograd recording
    (mirrors ndarray/register.py invoke for registry ops)."""
    out_arr = kwargs.pop("out", None)
    if kwargs.get("where") is not None:
        raise TypeError("the where= ufunc argument is not supported "
                        "(the reference's mx.np rejects it too)")
    kwargs.pop("where", None)

    leaves, treedef = jax.tree_util.tree_flatten(
        (list(args), kwargs), is_leaf=lambda x: isinstance(x, NDArray))
    slots = [i for i, v in enumerate(leaves) if isinstance(v, NDArray)]
    nd_inputs = [leaves[i] for i in slots]
    datas = tuple(a._data for a in nd_inputs)

    def fwd(*xs):
        new_leaves = list(leaves)
        for s, x in zip(slots, xs):
            new_leaves[s] = x
        a, kw = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return fn(*a, **kw)

    # builtins.any/all: this module also defines np.any/np.all at top level
    recording = (autograd.is_recording() and len(datas) > 0
                 and builtins.any(_is_inexact(d.dtype) for d in datas))
    if recording:
        out, vjp_fn = jax.vjp(fwd, *datas)
    else:
        out = fwd(*datas)

    def wrap(o):
        return ndarray(o) if isinstance(o, jax.Array) or _is_tracer(o) else o

    multi = isinstance(out, (tuple, list))
    raw_outs = list(out) if multi else [out]
    outs = [wrap(o) for o in raw_outs]

    if recording and builtins.all(isinstance(o, ndarray) for o in outs) \
            and builtins.all(_is_inexact(o.dtype) for o in raw_outs):
        node = autograd.record_op(op_name or getattr(fn, "__name__", "np_op"),
                                  outs, nd_inputs, vjp_fn)
        node.fwd_fn = fwd
    if out_arr is not None and not multi:
        out_arr._data = outs[0]._data
        out_arr._autograd_entry = outs[0]._autograd_entry
        return out_arr
    return tuple(outs) if multi else outs[0]


class ndarray(NDArray):
    """NumPy-semantics array (ref: python/mxnet/numpy/multiarray.py:75
    ndarray). Zero-dim and zero-size shapes are first-class; operators
    follow NumPy type promotion (jnp's), not the legacy NDArray rules."""

    __slots__ = ()

    # -- conversion bridges (ref: multiarray.py as_nd_ndarray) -----------
    def as_nd_ndarray(self):
        out = NDArray(self._data, ctx=self._ctx)
        out._autograd_entry = self._autograd_entry
        return out

    def as_np_ndarray(self):
        return self

    @property
    def grad(self):
        g = self._grad
        if g is not None and not isinstance(g, ndarray):
            g = ndarray(g._data, ctx=g._ctx)
        return g

    # -- operators with NumPy promotion ----------------------------------
    def _binop(self, name, other, reverse=False):
        if isinstance(other, (list, tuple, _onp.ndarray)):
            other = array(other)
        fn = _BINOP_FNS[name]
        a, b = (other, self) if reverse else (self, other)
        return _np_invoke(fn, (a, b), {}, op_name=name)

    def __neg__(self):
        return _np_invoke(jnp.negative, (self,), {})

    def __abs__(self):
        return _np_invoke(jnp.abs, (self,), {})

    def __matmul__(self, other):
        return _np_invoke(jnp.matmul, (self, other), {})

    def __rmatmul__(self, other):
        return _np_invoke(jnp.matmul, (other, self), {})

    def __floordiv__(self, other):
        return self._binop("floor_divide", other)

    def __rfloordiv__(self, other):
        return self._binop("floor_divide", other, True)

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an ndarray with more than "
                             "one element is ambiguous")
        return bool(self.item())

    def __getitem__(self, key):
        out = super().__getitem__(key)
        return ndarray._adopt(out)

    @classmethod
    def _adopt(cls, arr):
        """Re-brand a base NDArray result as np.ndarray, keeping its tape
        entry so backward() still works through it."""
        if isinstance(arr, cls):
            return arr
        out = cls(arr._data, ctx=arr._ctx)
        out._autograd_entry = arr._autograd_entry
        return out

    # -- NumPy-style methods ---------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        order = kwargs.pop("order", "C")
        if order != "C":
            raise NotImplementedError("only order='C' is supported")
        return _np_invoke(jnp.reshape, (self, shape), {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _np_invoke(jnp.transpose, (self,), {"axes": axes})

    @property
    def T(self):
        return self.transpose()

    def astype(self, dtype, copy=True):
        return _np_invoke(
            lambda x: x.astype(canonical_dtype(dtype)), (self,), {})

    def copy(self):
        return ndarray(self._data, ctx=self._ctx)

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def flatten(self, order="C"):
        return self.reshape(-1)

    def ravel(self, order="C"):
        return self.reshape(-1)

    def squeeze(self, axis=None):
        return _np_invoke(jnp.squeeze, (self,), {"axis": axis})

    def repeat(self, repeats, axis=None):
        return _np_invoke(jnp.repeat, (self,),
                          {"repeats": repeats, "axis": axis})

    def take(self, indices, axis=None, mode="raise"):
        idx = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(indices)
        if mode == "raise":
            # XLA can't raise from device code; check eagerly when concrete
            # (tracers fall back to clip, like the reference's npx take)
            if not _is_tracer(idx) and not _is_tracer(self._data):
                n = self.size if axis is None else self.shape[axis]
                host = _onp.asarray(idx)
                if host.size and (host.min() < -n or host.max() >= n):
                    raise IndexError(
                        "index out of range for take (size %d)" % n)
            mode = "clip"
        return _np_invoke(
            lambda x: jnp.take(x, idx, axis=axis, mode=mode), (self,), {})

    def clip(self, min=None, max=None):
        return _np_invoke(jnp.clip, (self, min, max), {})

    def round(self, decimals=0):
        return _np_invoke(jnp.round, (self,), {"decimals": decimals})

    def nonzero(self):
        return tuple(ndarray(i) for i in jnp.nonzero(self._data))

    def dot(self, b):
        return _np_invoke(jnp.dot, (self, b), {})

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    # -- NumPy dispatch protocol (ref: python/mxnet/numpy_dispatch_protocol
    # .py — onp.mean(mx_array) etc. dispatch to the mx implementation) ----
    def __array_function__(self, func, types, args, kwargs):
        import sys
        mod = sys.modules[__name__.rsplit(".", 1)[0]]  # mxnet_tpu.numpy
        impl = getattr(mod, func.__name__, None)
        if impl is None and func.__module__ == "numpy.linalg":
            impl = getattr(mod.linalg, func.__name__, None)
        if impl is None:
            return NotImplemented
        return impl(*args, **kwargs)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__":
            return NotImplemented
        import sys
        mod = sys.modules[__name__.rsplit(".", 1)[0]]
        impl = getattr(mod, ufunc.__name__, None)
        if impl is None:
            return NotImplemented
        return impl(*inputs, **kwargs)

    def __repr__(self):
        arr = self.asnumpy()
        prefix = "array("
        body = _onp.array2string(arr, separator=", ", prefix=prefix)
        dt = "" if arr.dtype in (_onp.float32, _onp.int64, _onp.bool_) \
            else ", dtype=%s" % arr.dtype
        ctx = self.context
        dev = "" if ctx.device_type == "cpu" else ", ctx=%s" % str(ctx)
        return "%s%s%s%s)" % (prefix, body, dt, dev)

    def __str__(self):
        return str(self.asnumpy())


def _reduce_method(fn_name):
    fn = getattr(jnp, fn_name)

    def method(self, axis=None, dtype=None, out=None, keepdims=False):
        kw = {"axis": axis, "keepdims": keepdims}
        if fn_name in ("sum", "prod", "cumsum", "cumprod", "mean", "std",
                       "var") and dtype is not None:
            kw["dtype"] = canonical_dtype(dtype)
        if fn_name in ("cumsum", "cumprod"):
            kw.pop("keepdims")
        if fn_name in ("argmax", "argmin"):
            kw.pop("keepdims")
        res = _np_invoke(fn, (self,), kw, op_name=fn_name)
        if out is not None:
            out._data = res._data
            out._autograd_entry = res._autograd_entry
            return out
        return res
    method.__name__ = fn_name
    return method


for _name in ("sum", "prod", "mean", "std", "var", "max", "min", "argmax",
              "argmin", "cumsum", "cumprod", "all", "any"):
    setattr(ndarray, _name, _reduce_method(_name))

_BINOP_FNS = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.true_divide, "mod": jnp.mod, "power": jnp.power,
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "lesser": jnp.less, "lesser_equal": jnp.less_equal,
    "floor_divide": jnp.floor_divide,
}


# ---------------------------------------------------------------------------
# creation functions
# ---------------------------------------------------------------------------

def _dev_wrap(data, ctx=None):
    ctx = ctx or current_context()
    return ndarray(_place(data, ctx) if not _is_tracer(data) else data,
                   ctx=ctx)


def array(object, dtype=None, ctx=None):
    """ref: multiarray.py array(). Float input defaults to float32 (the
    reference's np default dtype), ints keep their width. Delegates to the
    nd-level array() so the dtype policy lives in one place."""
    from ..ndarray.ndarray import array as _nd_array
    if isinstance(object, NDArray) and dtype is not None:
        return _dev_wrap(object._data.astype(canonical_dtype(dtype)), ctx)
    return ndarray._adopt(_nd_array(object, ctx=ctx, dtype=dtype))


def asarray(a, dtype=None, ctx=None):
    return array(a, dtype=dtype, ctx=ctx)


def zeros(shape, dtype=float32, order="C", ctx=None):
    return _dev_wrap(jnp.zeros(shape, canonical_dtype(dtype or float32)), ctx)


def ones(shape, dtype=float32, order="C", ctx=None):
    return _dev_wrap(jnp.ones(shape, canonical_dtype(dtype or float32)), ctx)


def full(shape, fill_value, dtype=None, order="C", ctx=None, out=None):
    if dtype is not None:
        dtype = canonical_dtype(dtype)
    fv = fill_value._data if isinstance(fill_value, NDArray) else fill_value
    res = _dev_wrap(jnp.full(shape, fv, dtype), ctx)
    if out is not None:
        out._data = res._data
        return out
    return res


def empty(shape, dtype=float32, order="C", ctx=None):
    return zeros(shape, dtype=dtype, ctx=ctx)


def empty_like(prototype, dtype=None, order="C"):
    p = prototype._data if isinstance(prototype, NDArray) else prototype
    return ndarray(jnp.zeros_like(
        p, dtype=canonical_dtype(dtype) if dtype else None))


def zeros_like(a, dtype=None, order="C", ctx=None):
    return _np_invoke(
        lambda x: jnp.zeros_like(
            x, dtype=canonical_dtype(dtype) if dtype else None), (a,), {})


def ones_like(a, dtype=None, order="C", ctx=None):
    return _np_invoke(
        lambda x: jnp.ones_like(
            x, dtype=canonical_dtype(dtype) if dtype else None), (a,), {})


def full_like(a, fill_value, dtype=None, order="C", ctx=None):
    return _np_invoke(
        lambda x: jnp.full_like(
            x, fill_value, dtype=canonical_dtype(dtype) if dtype else None),
        (a,), {})


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    if dtype is not None:
        dtype = canonical_dtype(dtype)
    # reference defaults arange to float32 unless dtype given int
    if dtype is None:
        dtype = _onp.float32
    return _dev_wrap(jnp.arange(start, stop, step, dtype=dtype), ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    res = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=canonical_dtype(dtype) if dtype else None,
                       axis=axis)
    if retstep:
        return _dev_wrap(res[0], ctx), float(res[1])
    return _dev_wrap(res, ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None):
    return _dev_wrap(
        jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                     dtype=canonical_dtype(dtype) if dtype else None,
                     axis=axis), ctx)


def eye(N, M=None, k=0, dtype=float32, ctx=None):
    return _dev_wrap(jnp.eye(N, M, k=k, dtype=canonical_dtype(dtype)), ctx)


def identity(n, dtype=float32, ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def hanning(M, dtype=float32, ctx=None):
    """ref: src/operator/numpy/np_window_op.cc _npi_hanning."""
    from ..ops.misc_tail import hanning as _h
    return _dev_wrap(_h(M=M, dtype=canonical_dtype(dtype)), ctx)


def hamming(M, dtype=float32, ctx=None):
    """ref: src/operator/numpy/np_window_op.cc _npi_hamming."""
    from ..ops.misc_tail import hamming as _h
    return _dev_wrap(_h(M=M, dtype=canonical_dtype(dtype)), ctx)


def blackman(M, dtype=float32, ctx=None):
    """ref: src/operator/numpy/np_window_op.cc _npi_blackman."""
    from ..ops.misc_tail import blackman as _b
    return _dev_wrap(_b(M=M, dtype=canonical_dtype(dtype)), ctx)


def copy(a):
    return array(a)


# ---------------------------------------------------------------------------
# generated jnp-delegating functions (ref: multiarray.py's ~240 op defs)
# ---------------------------------------------------------------------------

_DELEGATED = [
    # elementwise math
    "abs", "absolute", "add", "subtract", "multiply", "divide",
    "true_divide", "floor_divide", "mod", "remainder", "fmod", "power",
    "float_power", "sqrt", "cbrt", "square", "reciprocal", "negative",
    "positive", "sign", "exp", "expm1", "log", "log2", "log10", "log1p",
    "logaddexp", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "arctan2", "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "degrees", "radians", "deg2rad", "rad2deg", "hypot", "copysign",
    "fabs", "ceil", "floor", "trunc", "fix", "rint", "around", "round",
    "clip", "maximum", "minimum", "fmax", "fmin", "nan_to_num", "interp",
    "gcd", "lcm", "ldexp", "heaviside", "sinc", "i0",
    # logic / comparison
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isfinite",
    "isinf", "isnan", "isneginf", "isposinf", "isclose", "allclose",
    "array_equal", "array_equiv",
    # bitwise
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift",
    # reductions / statistics
    "sum", "prod", "mean", "std", "var", "median", "average", "ptp",
    "percentile", "quantile", "nansum", "nanprod", "nanmean", "nanstd",
    "nanvar", "nanmax", "nanmin", "amax", "amin", "max", "min", "all",
    "any", "cumsum", "cumprod", "nancumsum", "nancumprod", "count_nonzero",
    "bincount", "histogram", "correlate", "cov", "corrcoef", "digitize",
    # sorting / searching / indexing
    "argmax", "argmin", "nanargmax", "nanargmin", "argsort", "sort",
    "lexsort", "partition", "argpartition", "searchsorted", "nonzero",
    "flatnonzero", "argwhere", "where", "extract", "take",
    "take_along_axis", "choose", "compress", "diag_indices_from",
    "unravel_index", "ravel_multi_index", "indices", "tril_indices",
    "triu_indices", "triu_indices_from", "tril_indices_from", "unique",
    # shape manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays",
    "atleast_1d", "atleast_2d", "atleast_3d", "concatenate", "stack",
    "vstack", "hstack", "dstack", "column_stack", "row_stack", "split",
    "array_split", "hsplit", "vsplit", "dsplit", "tile", "repeat",
    "flip", "fliplr", "flipud", "roll", "rot90", "pad", "insert",
    "delete", "append", "resize", "trim_zeros",
    # linear algebra (main namespace part)
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "trace", "diagonal", "diag", "diagflat", "tril",
    "triu", "vander",
    # misc
    "meshgrid", "diff", "ediff1d", "gradient", "convolve", "polyval",
    "real", "imag", "conj", "conjugate", "angle", "may_share_memory",
    "shares_memory", "result_type", "can_cast", "promote_types",
    "issubdtype", "ndim", "shape", "size", "iscomplex", "isreal",
    "isscalar", "union1d", "intersect1d", "setdiff1d", "in1d", "isin",
    "apply_along_axis", "piecewise", "select", "tril", "packbits",
    "unpackbits", "float_power",
]


def _make_fn(jfn, name):
    def fn(*args, **kwargs):
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            kwargs["dtype"] = canonical_dtype(kwargs["dtype"])
        return _np_invoke(jfn, args, kwargs, op_name=name)
    fn.__name__ = name
    fn.__doc__ = "mx.np.%s — NumPy-semantics op, delegates to jnp.%s\n" \
        "(ref: python/mxnet/numpy/multiarray.py %s)" % (name, name, name)
    return fn


def _populate(ns):
    # jnp.fix is deprecated (removal in jax 0.10); same semantics as trunc
    renamed = {"fix": getattr(jnp, "trunc", None)}
    for name in _DELEGATED:
        if name in ns:
            continue
        jfn = renamed.get(name) or getattr(jnp, name, None)
        if jfn is None:
            continue
        ns[name] = _make_fn(jfn, name)
        __all__.append(name)


_populate(globals())
