"""mx.np.linalg (ref: python/mxnet/numpy/linalg.py; backed in the
reference by src/operator/numpy/linalg/). Thin autograd-recording wrappers
over jnp.linalg — XLA lowers decompositions to TPU-friendly kernels."""
from __future__ import annotations

import jax.numpy as jnp

from .multiarray import _np_invoke

__all__ = ["norm", "svd", "cholesky", "inv", "det", "slogdet", "solve",
           "tensorinv", "tensorsolve", "pinv", "eig", "eigh", "eigvals",
           "eigvalsh", "qr", "lstsq", "matrix_rank", "matrix_power",
           "multi_dot", "cond"]


def _make(name):
    jfn = getattr(jnp.linalg, name)

    def fn(*args, **kwargs):
        return _np_invoke(jfn, args, kwargs, op_name="linalg." + name)
    fn.__name__ = name
    fn.__doc__ = "mx.np.linalg.%s (ref: python/mxnet/numpy/linalg.py)" % name
    return fn


for _n in __all__:
    if hasattr(jnp.linalg, _n):
        globals()[_n] = _make(_n)
