"""mx.np.random — NumPy-style samplers (ref: python/mxnet/numpy/random.py).

Each sampler draws a fresh key from the framework PRNG stream
(mxnet_tpu.random), so ``mx.np.random`` and ``mx.nd.random`` share one
seeded sequence like the reference's per-context sampler resources
(ref: src/resource.cc kRandom)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from .. import random as _random
from ..base import canonical_dtype
from .multiarray import ndarray, _dev_wrap, array as _array

__all__ = ["uniform", "normal", "randint", "rand", "randn", "choice",
           "shuffle", "permutation", "multinomial", "gamma", "beta",
           "exponential", "laplace", "gumbel", "logistic", "lognormal",
           "pareto", "power", "rayleigh", "weibull", "chisquare", "seed"]


def seed(s):
    _random.seed(s)


def _size_to_shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def _as_val(v):
    from ..ndarray.ndarray import NDArray
    return v._data if isinstance(v, NDArray) else v


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, out=None):
    dtype = canonical_dtype(dtype) if dtype else jnp.float32
    shape = _size_to_shape(size)
    low, high = _as_val(low), _as_val(high)
    res = jax.random.uniform(_random.next_key(), shape, dtype,
                             minval=low, maxval=high) \
        if not (hasattr(low, "shape") or hasattr(high, "shape")) else \
        jnp.asarray(low) + jax.random.uniform(
            _random.next_key(),
            jnp.broadcast_shapes(jnp.shape(low), jnp.shape(high), shape),
            dtype) * (jnp.asarray(high) - jnp.asarray(low))
    out_arr = _dev_wrap(res, ctx)
    if out is not None:
        out._data = out_arr._data
        return out
    return out_arr


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, out=None):
    dtype = canonical_dtype(dtype) if dtype else jnp.float32
    shape = jnp.broadcast_shapes(jnp.shape(_as_val(loc)),
                                 jnp.shape(_as_val(scale)),
                                 _size_to_shape(size))
    res = jnp.asarray(_as_val(loc)) + jnp.asarray(_as_val(scale)) * \
        jax.random.normal(_random.next_key(), shape, dtype)
    out_arr = _dev_wrap(res, ctx)
    if out is not None:
        out._data = out_arr._data
        return out
    return out_arr


def randn(*size):
    return normal(0.0, 1.0, size=size or None)


def rand(*size):
    return uniform(0.0, 1.0, size=size or None)


def randint(low, high=None, size=None, dtype=None, ctx=None, out=None):
    if high is None:
        low, high = 0, low
    # the reference defaults to int64; under jax's 32-bit default that
    # truncates with a warning, so default to the platform int instead
    dtype = canonical_dtype(dtype) if dtype is not None else jnp.int32
    res = jax.random.randint(_random.next_key(), _size_to_shape(size),
                             low, high, dtype=dtype)
    out_arr = _dev_wrap(res, ctx)
    if out is not None:
        out._data = out_arr._data
        return out
    return out_arr


def choice(a, size=None, replace=True, p=None, ctx=None, out=None):
    a_val = _as_val(a)
    if isinstance(a_val, int):
        a_val = jnp.arange(a_val)
    else:
        a_val = jnp.asarray(a_val)
    p_val = None if p is None else jnp.asarray(_as_val(p))
    res = jax.random.choice(_random.next_key(), a_val, _size_to_shape(size),
                            replace=replace, p=p_val)
    out_arr = _dev_wrap(res, ctx)
    if out is not None:
        out._data = out_arr._data
        return out
    return out_arr


def shuffle(x):
    """In-place shuffle along axis 0 (ref: numpy/random.py shuffle)."""
    perm = jax.random.permutation(_random.next_key(), x.shape[0])
    x._data = jnp.take(x._data, perm, axis=0)


def permutation(x):
    if isinstance(x, int):
        return ndarray(jax.random.permutation(_random.next_key(), x))
    arr = _array(x)
    perm = jax.random.permutation(_random.next_key(), arr.shape[0])
    return ndarray(jnp.take(arr._data, perm, axis=0))


def multinomial(n, pvals, size=None):
    pv = jnp.asarray(_as_val(pvals))
    shape = _size_to_shape(size)
    draws = jax.random.categorical(
        _random.next_key(), jnp.log(pv), shape=shape + (n,))
    counts = jax.vmap(lambda d: jnp.bincount(d, length=pv.shape[0]))(
        draws.reshape(-1, n)).reshape(shape + (pv.shape[0],))
    return ndarray(counts)


def _draw(transform, params, size, dtype, ctx):
    """Shared tail for the parametric samplers: broadcast the distribution
    parameters against ``size``, draw, place on the target context."""
    dtype = canonical_dtype(dtype) if dtype else jnp.float32
    vals = [jnp.asarray(_as_val(p), dtype) for p in params]
    shape = jnp.broadcast_shapes(*[v.shape for v in vals],
                                 _size_to_shape(size))
    return _dev_wrap(transform(_random.next_key(), shape, dtype, *vals), ctx)


def gamma(shape=1.0, scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(lambda k, s, dt, a, sc: jax.random.gamma(k, a, s, dt) * sc,
                 (shape, scale), size, dtype, ctx)


def beta(a=1.0, b=1.0, size=None, dtype=None, ctx=None):
    return _draw(lambda k, s, dt, av, bv: jax.random.beta(k, av, bv, s, dt),
                 (a, b), size, dtype, ctx)


def exponential(scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(lambda k, s, dt, sc: jax.random.exponential(k, s, dt) * sc,
                 (scale,), size, dtype, ctx)


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, lo, sc: lo + sc * jax.random.laplace(k, s, dt),
        (loc, scale), size, dtype, ctx)


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, lo, sc: lo + sc * jax.random.gumbel(k, s, dt),
        (loc, scale), size, dtype, ctx)


def logistic(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, lo, sc: lo + sc * jax.random.logistic(k, s, dt),
        (loc, scale), size, dtype, ctx)


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, m, sg:
        jnp.exp(m + sg * jax.random.normal(k, s, dt)),
        (mean, sigma), size, dtype, ctx)


def pareto(a=1.0, size=None, dtype=None, ctx=None):
    return _draw(lambda k, s, dt, av: jax.random.pareto(k, av, s, dt) - 1.0,
                 (a,), size, dtype, ctx)


def power(a=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, av: jax.random.uniform(k, s, dt) ** (1.0 / av),
        (a,), size, dtype, ctx)


def rayleigh(scale=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, sc:
        sc * jnp.sqrt(-2.0 * jnp.log1p(-jax.random.uniform(k, s, dt))),
        (scale,), size, dtype, ctx)


def weibull(a=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, av:
        (-jnp.log1p(-jax.random.uniform(k, s, dt))) ** (1.0 / av),
        (a,), size, dtype, ctx)


def chisquare(df=1.0, size=None, dtype=None, ctx=None):
    return _draw(
        lambda k, s, dt, d: 2.0 * jax.random.gamma(k, d / 2.0, s, dt),
        (df,), size, dtype, ctx)
