"""mx.np — NumPy-compatible array API (ref: python/mxnet/numpy/__init__.py).

``from mxnet_tpu import np`` gives the NumPy-semantics surface the reference
exposes as ``mx.np`` (zero-dim arrays, NumPy promotion/broadcasting), with
every op autograd-recordable and XLA-compiled."""
from . import random  # noqa: F401
from . import linalg  # noqa: F401
from .multiarray import *  # noqa: F401,F403
from .multiarray import ndarray, _np_invoke  # noqa: F401
