"""Execution-engine control surface.

TPU-native re-design of the reference's dependency engine
(ref: src/engine/, include/mxnet/engine.h:117). The reference schedules every
op through ThreadedEnginePerDevice with read/write variable queues
(ThreadedVar, src/engine/threaded_engine.h:120-229). On TPU that machinery is
replaced by JAX's async dispatch + XLA's dataflow ordering:

* ops return immediately with futures (``jax.Array`` is async) — the analog of
  ``Engine::PushAsync`` returning before the kernel runs;
* read-after-write ordering is enforced by SSA dataflow inside XLA programs
  and by the PJRT stream for program-to-program ordering — the analog of the
  per-var FIFO queues;
* ``WaitForVar`` ≙ ``block_until_ready`` on one array; ``WaitForAll`` ≙
  blocking on everything live.

What remains meaningful — and is implemented here — is the *control* surface:
engine-type selection (NaiveEngine ≙ force-synchronous dispatch for
debugging), bulking knobs (≙ how many ops a CachedOp fuses into one XLA
program), and exception semantics (async errors surface at the next sync
point, mirroring threaded_engine.cc:422-433).
"""
from __future__ import annotations

import contextlib
import os
import threading

from ._debug import locktrace as _locktrace
from .base import getenv as _getenv

__all__ = [
    "engine_type", "is_naive", "set_bulk_size", "bulk_size", "bulk",
    "wait_for_var", "wait_for_all", "push_sync",
]

_local = threading.local()


def engine_type():
    """Selected engine kind. ``MXNET_ENGINE_TYPE=NaiveEngine`` (ref:
    src/engine/engine.cc:32-48) forces synchronous execution: every op blocks
    until its result is ready — the serial-debugging mode of the reference."""
    return _getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def is_naive():
    return engine_type() == "NaiveEngine"


def maybe_sync(data):
    """Called by the op layer after dispatch; blocks under NaiveEngine so
    errors surface at the faulting op (serial debugging)."""
    if is_naive():
        import jax
        jax.block_until_ready(data)
    return data


_bulk_size = [int(_getenv("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))]  # mxlint: disable=MX003 (process-wide knob, GIL-atomic int store; per-thread segments snapshot it at scope entry)


def set_bulk_size(size):
    """Set the op-bulking segment limit (ref: Engine::set_bulk_size,
    MXNET_EXEC_BULK_EXEC_* env vars, graph_executor.cc:1288 InitOpSegs).
    Bounds how many queued imperative ops a bulk segment compiles into one
    XLA program; the default comes from MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN.
    Resizing is a segment boundary (any pending segment flushes first, as
    the reference flushes the current opr bulk). Returns the previous
    value."""
    prev = _bulk_size[0]
    _flush_pending_segment()
    _bulk_size[0] = int(size)
    _register().set_active_bulk_limit(int(size))
    return prev


def bulk_size():
    return _bulk_size[0]


_register_mod = None


def _register():
    """The op-dispatch module (lazy: ndarray imports engine, not vice
    versa at module load)."""
    global _register_mod
    if _register_mod is None:
        from .ndarray import register
        _register_mod = register
    return _register_mod


def _flush_pending_segment():
    """Drain this thread's imperative bulk segment, if any."""
    _register().flush_bulk_segment()


@contextlib.contextmanager
def bulk(size=None):
    """Scope form of set_bulk_size (ref: python/mxnet/engine.py bulk).

    Inside the scope, eligible imperative ops are ACCUMULATED into a lazy
    segment and executed as one jitted XLA program at a sync point (buffer
    read, wait_for_var/wait_for_all, autograd, or segment-full at
    ``bulk_size()`` ops) — the imperative analog of CachedOp bulking
    (ref: graph_executor.cc:1288 InitOpSegs). ``size=None`` keeps the
    current ``bulk_size()`` (the MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN
    default). With MXNET_IMPERATIVE_JIT=0 this degrades to the historical
    knob-only behavior (ops run eagerly)."""
    reg = _register()
    prev = set_bulk_size(size if size is not None else bulk_size())
    seg = None
    if reg.imperative_jit_enabled() and not is_naive():
        # size <= 1 still installs a segment (shadowing any outer one):
        # each op flushes as it queues, i.e. per-op synchronous execution
        # — the reference semantics of bulk size 1 inside a bulk scope
        seg = reg.begin_bulk_segment(max(1, bulk_size()))
    try:
        yield
    finally:
        try:
            if seg is not None:
                reg.end_bulk_segment(seg)
        finally:
            set_bulk_size(prev)


def wait_for_var(arr):
    """ref: Engine::WaitForVar (include/mxnet/engine.h). Blocks until the
    array's producing computation is done; raises its deferred error here.
    Reading ``_data`` drains any bulk segment the array is pending in."""
    import jax
    if _locktrace.ENABLED:
        _locktrace.boundary("engine.wait_for_var")
    data = getattr(arr, "_data", arr)
    jax.block_until_ready(data)


def wait_for_all():
    """ref: Engine::WaitForAll. Barrier over all live device work. The
    CALLING thread's pending bulk segment is flushed first — queued work
    this barrier must cover even though no jax.Array exists for it yet.
    Bulk segments are thread-local (like the reference's per-thread opr
    bulk): another thread's queued-but-unflushed ops are drained by that
    thread's own sync points / engine.bulk scope exit, not by this
    barrier. When profiling is on the barrier is a span in the ``bulk``
    lane — long bars here mean the device is behind the host."""
    import jax
    import time as _time
    from . import profiler as _profiler
    t0 = _time.perf_counter() if _profiler._LIVE else None
    if _locktrace.ENABLED:
        _locktrace.boundary("engine.wait_for_all")
    _flush_pending_segment()
    try:
        for d in jax.live_arrays():
            d.block_until_ready()
    except AttributeError:
        (jax.device_put(0.0) + 0).block_until_ready()
    if t0 is not None:
        _profiler.record_op("engine.wait_for_all",
                            (_time.perf_counter() - t0) * 1e6,
                            category="engine", lane="bulk")


def push_sync(fn, *args):
    """Run a host callback synchronously (ref: Engine::PushSync). The
    threaded scheduling of the reference is unnecessary — JAX dispatch is
    already async — so this simply invokes and blocks."""
    out = fn(*args)
    if out is not None:
        import jax
        jax.block_until_ready(out)
    return out
