"""Execution-engine control surface.

TPU-native re-design of the reference's dependency engine
(ref: src/engine/, include/mxnet/engine.h:117). The reference schedules every
op through ThreadedEnginePerDevice with read/write variable queues
(ThreadedVar, src/engine/threaded_engine.h:120-229). On TPU that machinery is
replaced by JAX's async dispatch + XLA's dataflow ordering:

* ops return immediately with futures (``jax.Array`` is async) — the analog of
  ``Engine::PushAsync`` returning before the kernel runs;
* read-after-write ordering is enforced by SSA dataflow inside XLA programs
  and by the PJRT stream for program-to-program ordering — the analog of the
  per-var FIFO queues;
* ``WaitForVar`` ≙ ``block_until_ready`` on one array; ``WaitForAll`` ≙
  blocking on everything live.

What remains meaningful — and is implemented here — is the *control* surface:
engine-type selection (NaiveEngine ≙ force-synchronous dispatch for
debugging), bulking knobs (≙ how many ops a CachedOp fuses into one XLA
program), and exception semantics (async errors surface at the next sync
point, mirroring threaded_engine.cc:422-433).
"""
from __future__ import annotations

import contextlib
import os
import threading

__all__ = [
    "engine_type", "is_naive", "set_bulk_size", "bulk_size", "bulk",
    "wait_for_var", "wait_for_all", "push_sync",
]

_local = threading.local()


def engine_type():
    """Selected engine kind. ``MXNET_ENGINE_TYPE=NaiveEngine`` (ref:
    src/engine/engine.cc:32-48) forces synchronous execution: every op blocks
    until its result is ready — the serial-debugging mode of the reference."""
    return os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def is_naive():
    return engine_type() == "NaiveEngine"


def maybe_sync(data):
    """Called by the op layer after dispatch; blocks under NaiveEngine so
    errors surface at the faulting op (serial debugging)."""
    if is_naive():
        import jax
        jax.block_until_ready(data)
    return data


_bulk_size = [int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))]


def set_bulk_size(size):
    """Set the op-bulking segment limit (ref: Engine::set_bulk_size,
    MXNET_EXEC_BULK_EXEC_* env vars, graph_executor.cc:1288 InitOpSegs).
    Here it bounds how many traced ops a CachedOp compiles into one XLA
    program segment. Returns the previous value."""
    prev = _bulk_size[0]
    _bulk_size[0] = int(size)
    return prev


def bulk_size():
    return _bulk_size[0]


@contextlib.contextmanager
def bulk(size):
    """Scope form of set_bulk_size (ref: python/mxnet/engine.py bulk)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_for_var(arr):
    """ref: Engine::WaitForVar (include/mxnet/engine.h). Blocks until the
    array's producing computation is done; raises its deferred error here."""
    import jax
    data = getattr(arr, "_data", arr)
    jax.block_until_ready(data)


def wait_for_all():
    """ref: Engine::WaitForAll. Barrier over all live device work."""
    import jax
    try:
        for d in jax.live_arrays():
            d.block_until_ready()
    except AttributeError:
        (jax.device_put(0.0) + 0).block_until_ready()


def push_sync(fn, *args):
    """Run a host callback synchronously (ref: Engine::PushSync). The
    threaded scheduling of the reference is unnecessary — JAX dispatch is
    already async — so this simply invokes and blocks."""
    out = fn(*args)
    if out is not None:
        import jax
        jax.block_until_ready(out)
    return out
