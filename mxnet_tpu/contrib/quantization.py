"""INT8 quantization (ref: python/mxnet/contrib/quantization.py; kernels
src/operator/quantization/, graph pass quantize_graph_pass.cc).

TPU-native re-design: the reference rewrites the nnvm graph to insert
quantize/dequantize/requantize nodes and swaps FCs/convs for INT8 kernels.
Here quantization is a Gluon-level transform — ``quantize_net`` replaces
Dense/Conv2D children with quantized twins whose weights are stored int8
(per-channel symmetric scales) and whose matmul runs int8xint8→int32 on
the MXU via ``preferred_element_type`` (XLA's native INT8 path), then
dequantizes fused into the epilogue. Calibration modes match the
reference: 'naive' (min/max over calibration batches) and 'entropy'
(KL-optimal thresholds, quantization.py:_get_optimal_thresholds).
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as _np

from .. import ndarray as nd
from ..gluon import nn as _nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray

__all__ = ["quantize_net", "calib_graph", "CalibrationCollector",
           "quantize", "dequantize", "requantize",
           "_get_optimal_threshold"]


# -- primitive ops (ref: src/operator/quantization/quantize.cc etc.) --------

def quantize(data, min_range, max_range, out_type="int8"):
    """Affine-quantize float data to int8 given calibrated range
    (ref: quantize.cc QuantizeCompute — symmetric MKLDNN-style)."""
    x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    amax = jnp.maximum(jnp.abs(jnp.asarray(min_range, x.dtype)),
                       jnp.abs(jnp.asarray(max_range, x.dtype)))
    scale = 127.0 / jnp.maximum(amax, 1e-8)
    q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
    return (NDArray(q), NDArray(-amax), NDArray(amax)) \
        if isinstance(data, NDArray) else (q, -amax, amax)


def dequantize(data, min_range, max_range, out_type="float32"):
    """ref: dequantize.cc."""
    q = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    amax = jnp.maximum(jnp.abs(jnp.asarray(
        min_range._data if isinstance(min_range, NDArray) else min_range)),
        jnp.abs(jnp.asarray(
            max_range._data if isinstance(max_range, NDArray)
            else max_range)))
    x = q.astype(jnp.float32) * (amax / 127.0)
    return NDArray(x) if isinstance(data, NDArray) else x


def requantize(data, min_range, max_range, out_min, out_max):
    """int32 accumulator → int8 with new range (ref: requantize.cc)."""
    x = dequantize(data, min_range, max_range)
    return quantize(x, out_min, out_max)


# -- calibration (ref: quantization.py _LayerOutputCollector /
#    _LayerOutputMinMaxCollector / _get_optimal_thresholds) ----------------

def _smooth_distribution(p, eps=1e-4):
    """Replace zeros with eps, taking the mass off non-zero entries
    (ref: src/operator/quantization/calibrate.cc SmoothDistribution)."""
    is_zero = p == 0
    n_zeros = int(is_zero.sum())
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        return None
    eps1 = eps * n_zeros / n_nonzeros
    if eps1 >= 1.0:
        return None
    return p + eps * is_zero - eps1 * (~is_zero)


def _get_optimal_threshold(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold from a symmetric histogram.
    Faithful re-derivation of the TensorRT-style sweep in the reference
    (ref: src/operator/quantization/calibrate.cc CalibrateComputeCPU):
    for each candidate window, ``p`` folds the clipped outlier mass into
    its edge bins while ``q`` (the int8-quantized reconstruction) has none
    there — so KL(p||q) grows with clipped mass and the sweep balances
    clip error against resolution."""
    hist = _np.asarray(hist, dtype=_np.float64)
    hist_edges = _np.asarray(hist_edges, dtype=_np.float64)
    num_bins = hist.size
    zero_bin = num_bins // 2
    half_q = num_quantized_bins // 2
    thresholds = []
    divergences = []
    for i in range(half_q, zero_bin + 1):
        start, stop = zero_bin - i, zero_bin + i + 1
        sliced = hist[start + 1:stop - 1]
        p = _np.zeros(stop - start)
        p[0] = hist[:start + 1].sum()
        p[-1] = hist[stop - 1:].sum()
        p[1:-1] = sliced
        # q: quantize the window WITHOUT the folded outliers
        sliced_full = _np.zeros_like(p)
        sliced_full[1:-1] = sliced
        nmerged = p.size // num_quantized_bins
        q = _np.zeros_like(p)
        for j in range(num_quantized_bins):
            s = j * nmerged
            t = p.size if j == num_quantized_bins - 1 else (j + 1) * nmerged
            chunk = sliced_full[s:t]
            nz = int((chunk != 0).sum())
            if nz:
                q[s:t] = _np.where((p[s:t] != 0), chunk.sum() / nz, 0.0)
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        thresholds.append(float(hist_edges[min(stop, num_bins)]))
        if ps is None or qs is None:
            divergences.append(_np.inf)
            continue
        pn, qn = ps / ps.sum(), qs / qs.sum()
        divergences.append(float((pn * _np.log(pn / qn)).sum()))
    if not thresholds:
        return float(abs(hist_edges[-1]))
    return thresholds[int(_np.argmin(divergences))]


class CalibrationCollector:
    """Accumulates per-layer input statistics during calibration forwards
    (ref: quantization.py _LayerOutputMinMaxCollector)."""

    def __init__(self, mode="naive", num_bins=8001):
        assert mode in ("naive", "entropy")
        self.mode = mode
        self.num_bins = num_bins
        self.min_max = {}     # name -> (min, max)
        self.hists = {}       # name -> (hist, edges)

    def collect(self, name, arr):
        a = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(arr)
        mn, mx = float(a.min()), float(a.max())
        if name in self.min_max:
            pmn, pmx = self.min_max[name]
            mn, mx = min(mn, pmn), max(mx, pmx)
        self.min_max[name] = (mn, mx)
        if self.mode == "entropy":
            amax = max(abs(mn), abs(mx), 1e-8)
            prev = self.hists.get(name)
            if prev is not None and prev[1][-1] >= amax:
                # new batch fits the existing range: accumulate in place
                self.hists[name] = (prev[0] + _np.histogram(
                    a, bins=self.num_bins,
                    range=(prev[1][0], prev[1][-1]))[0], prev[1])
            else:
                hist, edges = _np.histogram(a, bins=self.num_bins,
                                            range=(-amax, amax))
                if prev is not None:
                    # range grew: fold the old histogram into the new,
                    # wider bins via its bin centers (approximate re-bin —
                    # keeps ALL batches' statistics, not just the last)
                    old_hist, old_edges = prev
                    centers = (old_edges[:-1] + old_edges[1:]) / 2.0
                    hist += _np.histogram(centers, bins=self.num_bins,
                                          range=(-amax, amax),
                                          weights=old_hist)[0]
                self.hists[name] = (hist, edges)

    def threshold(self, name):
        if self.mode == "entropy" and name in self.hists:
            hist, edges = self.hists[name]
            return _get_optimal_threshold(hist, edges)
        mn, mx = self.min_max.get(name, (0.0, 1.0))
        return max(abs(mn), abs(mx), 1e-8)


# -- quantized layers -------------------------------------------------------

class _QuantizedDense(HybridBlock):
    """INT8 Dense: weight stored int8 with per-output-channel scales;
    activations quantized with the calibrated threshold; int8xint8→int32
    matmul on the MXU (ref: quantized_fully_connected.cc)."""

    def __init__(self, dense, act_threshold, prefix=None):
        super().__init__(prefix=prefix or dense.prefix)
        w = dense.weight.data()._data  # (out, in)
        w_scale = jnp.maximum(jnp.abs(w).max(axis=1), 1e-8) / 127.0
        self._wq = jnp.clip(jnp.round(w / w_scale[:, None]),
                            -127, 127).astype(jnp.int8)
        self._w_scale = w_scale
        self._bias = dense.bias.data()._data if dense.bias is not None \
            else None
        self._act_scale = float(act_threshold) / 127.0
        self._units = dense._units
        self._flatten = dense._flatten
        self.act = dense.act

    def forward(self, x, *args):
        xd = x._data if isinstance(x, NDArray) else x
        if self._flatten and xd.ndim > 2:
            xd = xd.reshape(xd.shape[0], -1)
        xq = jnp.clip(jnp.round(xd / self._act_scale),
                      -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, self._wq.T, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (self._act_scale * self._w_scale)
        if self._bias is not None:
            out = out + self._bias
        res = NDArray(out) if isinstance(x, NDArray) else out
        if self.act is not None:
            res = self.act(res)
        return res


class _QuantizedConv2D(HybridBlock):
    """INT8 Conv2D (NCHW) with per-output-channel weight scales
    (ref: quantized_conv.cc)."""

    def __init__(self, conv, act_threshold, prefix=None):
        super().__init__(prefix=prefix or conv.prefix)
        w = conv.weight.data()._data  # (O, I, kH, kW)
        w_scale = jnp.maximum(
            jnp.abs(w).reshape(w.shape[0], -1).max(axis=1), 1e-8) / 127.0
        self._wq = jnp.clip(
            jnp.round(w / w_scale[:, None, None, None]),
            -127, 127).astype(jnp.int8)
        self._w_scale = w_scale
        self._bias = conv.bias.data()._data if conv.bias is not None \
            else None
        self._act_scale = float(act_threshold) / 127.0
        self._strides = conv._kwargs.get("stride", (1, 1))
        self._padding = conv._kwargs.get("pad", (0, 0))
        self._dilation = conv._kwargs.get("dilate", (1, 1))
        self.act = getattr(conv, "act", None)

    def forward(self, x, *args):
        xd = x._data if isinstance(x, NDArray) else x
        xq = jnp.clip(jnp.round(xd / self._act_scale),
                      -127, 127).astype(jnp.int8)
        pad = [(int(p), int(p)) for p in self._padding]
        acc = jax.lax.conv_general_dilated(
            xq, self._wq, window_strides=[int(s) for s in self._strides],
            padding=pad, rhs_dilation=[int(d) for d in self._dilation],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * \
            (self._act_scale * self._w_scale)[None, :, None, None]
        if self._bias is not None:
            out = out + self._bias[None, :, None, None]
        res = NDArray(out) if isinstance(x, NDArray) else out
        if self.act is not None:
            res = self.act(res)
        return res


# -- driver -----------------------------------------------------------------

def _walk_children(block, prefix=""):
    """Yield (parent, local_name, path, child) with dot-separated paths so
    nested blocks with the same local name ('0' in two branches) stay
    distinct in calibration stats and exclude matching."""
    for name, child in list(block._children.items()):
        path = prefix + name if not prefix else prefix + "." + name
        yield block, name, path, child
        yield from _walk_children(child, path)


def _iter_blocks(block):
    yield block
    for _, _, _, child in _walk_children(block):
        yield child


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None,
                 num_calib_examples=None, logger=None):
    """Quantize a Gluon network's Dense/Conv2D layers to INT8
    (ref: quantization.py:quantize_net). ``calib_data`` is an iterable of
    input batches (NDArray or tuple); with ``calib_mode='none'`` a
    conservative default range is used."""
    assert quantized_dtype in ("int8", "auto"), \
        "only int8 quantization is supported"
    exclude = set(exclude_layers or [])
    collector = CalibrationCollector(
        mode=calib_mode if calib_mode != "none" else "naive")

    targets = [(parent, name, path, child)
               for parent, name, path, child in _walk_children(network)
               if isinstance(child, (_nn.Dense, _nn.Conv2D))
               and name not in exclude and path not in exclude
               and child.__class__.__name__ not in exclude
               and getattr(child, "_groups", 1) == 1
               and (isinstance(child, _nn.Dense)
                    or child._kwargs.get("layout") == "NCHW")]

    if calib_data is not None and calib_mode != "none":
        # capture each target layer's input by hooking forward; a
        # hybridized net runs its cached XLA graph and never calls child
        # forwards, so force the eager path for the calibration passes
        hybrid_state = [(blk, blk._active)
                        for blk in _iter_blocks(network)
                        if hasattr(blk, "_active")]
        for blk, _ in hybrid_state:
            blk._active = False
        hooks = []
        for _, _, path, child in targets:
            orig = child.forward

            def hooked(x, *a, _name=path, _orig=orig, **kw):
                collector.collect(_name, x)
                return _orig(x, *a, **kw)
            child.forward = hooked
            hooks.append((child, orig))
        seen = 0
        try:
            for batch in calib_data:
                data = batch[0] if isinstance(batch, (tuple, list)) \
                    else batch
                if not isinstance(data, NDArray):
                    data = nd.array(data)
                network(data)
                seen += data.shape[0]
                if num_calib_examples is not None and \
                        seen >= num_calib_examples:
                    break
        finally:
            for child, orig in hooks:
                child.forward = orig
            for blk, active in hybrid_state:
                blk._active = active
        (logger or logging).info(
            "Calibrated %d layers on %d examples (%s mode)",
            len(targets), seen, collector.mode)

    for parent, name, path, child in targets:
        thr = collector.threshold(path)
        if isinstance(child, _nn.Dense):
            q = _QuantizedDense(child, thr)
        else:
            q = _QuantizedConv2D(child, thr)
        parent._children[name] = q
        if hasattr(parent, name):
            setattr(parent, name, q)
    # stale compiled graphs would still run the fp32 layers
    for blk in _iter_blocks(network):
        if hasattr(blk, "_cached_graph"):
            blk._cached_graph = {}
    return network


def calib_graph(qsym, arg_params, aux_params, collector, calib_mode="naive",
                quantized_dtype="int8", logger=None):
    """Symbolic-path shim kept for API parity (ref: quantization.py
    calib_graph). The gluon path (quantize_net) is the primary flow."""
    raise NotImplementedError(
        "symbolic calib_graph is not implemented; use quantize_net on a "
        "Gluon network (SymbolBlock wraps symbolic models)")
