"""ONNX ModelProto -> Symbol graph import.

ref: python/mxnet/contrib/onnx/onnx2mx/_op_translations.py +
import_model.py / import_onnx.py GraphProto.from_onnx. Returns
(sym, arg_params, aux_params) exactly like the reference.
"""
from __future__ import annotations

import numpy as np

from . import proto as P

__all__ = ["import_graph"]


def _pads_to_mx(pads):
    if not pads:
        return (0, 0)
    k = len(pads) // 2
    begin, end = pads[:k], pads[k:]
    if list(begin) != list(end):
        raise NotImplementedError("asymmetric ONNX pads %r" % (pads,))
    return tuple(int(p) for p in begin)


def _conv(sym, ins, attrs, name, initializers):
    kwargs = dict(kernel=tuple(attrs["kernel_shape"]),
                  stride=tuple(attrs.get("strides", (1, 1))),
                  dilate=tuple(attrs.get("dilations", (1, 1))),
                  pad=_pads_to_mx(attrs.get("pads")),
                  num_group=int(attrs.get("group", 1)))
    weight = initializers[ins[1].name]
    kwargs["num_filter"] = int(weight.shape[0])
    if len(ins) == 2:
        return sym.Convolution(ins[0].sym, ins[1].sym, no_bias=True,
                               name=name, **kwargs)
    return sym.Convolution(ins[0].sym, ins[1].sym, ins[2].sym,
                           no_bias=False, name=name, **kwargs)


def _deconv(sym, ins, attrs, name, initializers):
    kwargs = dict(kernel=tuple(attrs["kernel_shape"]),
                  stride=tuple(attrs.get("strides", (1, 1))),
                  dilate=tuple(attrs.get("dilations", (1, 1))),
                  pad=_pads_to_mx(attrs.get("pads")),
                  num_group=int(attrs.get("group", 1)))
    weight = initializers[ins[1].name]
    kwargs["num_filter"] = int(weight.shape[1]) * kwargs["num_group"]
    args = [i.sym for i in ins]
    return sym.Deconvolution(*args, no_bias=(len(ins) == 2), name=name,
                             **kwargs)


def _bn(sym, ins, attrs, name, initializers):
    return sym.BatchNorm(*[i.sym for i in ins], name=name,
                         eps=float(attrs.get("epsilon", 1e-5)),
                         momentum=float(attrs.get("momentum", 0.9)),
                         fix_gamma=False, use_global_stats=False)


def _gemm(sym, ins, attrs, name, initializers):
    if attrs.get("transA", 0):
        raise NotImplementedError("Gemm transA=1")
    weight = initializers.get(ins[1].name)
    if not attrs.get("transB", 0):
        if weight is None:
            raise NotImplementedError("Gemm transB=0 with dynamic B")
        initializers[ins[1].name] = np.ascontiguousarray(weight.T)
        weight = initializers[ins[1].name]
    num_hidden = int(weight.shape[0])
    args = [i.sym for i in ins]
    return sym.FullyConnected(*args, num_hidden=num_hidden,
                              no_bias=(len(ins) == 2), flatten=False,
                              name=name)


def _pool(ptype, global_pool):
    def f(sym, ins, attrs, name, initializers):
        if global_pool:
            return sym.Pooling(ins[0].sym, pool_type=ptype,
                               global_pool=True, kernel=(1, 1), name=name)
        kwargs = dict(kernel=tuple(attrs["kernel_shape"]),
                      stride=tuple(attrs.get("strides", (1, 1))),
                      pad=_pads_to_mx(attrs.get("pads")),
                      pool_type=ptype,
                      pooling_convention=("full" if attrs.get("ceil_mode")
                                          else "valid"))
        if ptype == "avg":
            kwargs["count_include_pad"] = \
                bool(attrs.get("count_include_pad", 1))
        return sym.Pooling(ins[0].sym, name=name, **kwargs)
    return f


def _act(mx_type):
    def f(sym, ins, attrs, name, initializers):
        return sym.Activation(ins[0].sym, act_type=mx_type, name=name)
    return f


def _binop(op_name):
    def f(sym, ins, attrs, name, initializers):
        return getattr(sym, op_name)(ins[0].sym, ins[1].sym, name=name)
    return f


def _flatten(sym, ins, attrs, name, initializers):
    return sym.Flatten(ins[0].sym, name=name)


def _concat(sym, ins, attrs, name, initializers):
    return sym.concat(*[i.sym for i in ins],
                      dim=int(attrs.get("axis", 1)), name=name)


def _softmax(sym, ins, attrs, name, initializers):
    return sym.softmax(ins[0].sym, axis=int(attrs.get("axis", -1)),
                       name=name)


def _dropout(sym, ins, attrs, name, initializers):
    return sym.Dropout(ins[0].sym, name=name)


def _reshape(sym, ins, attrs, name, initializers):
    shape = initializers.get(ins[1].name) if len(ins) > 1 else \
        np.asarray(attrs.get("shape", ()))
    if shape is None:
        raise NotImplementedError("Reshape with dynamic shape input")
    return sym.Reshape(ins[0].sym, shape=tuple(int(s) for s in shape),
                       name=name)


def _transpose(sym, ins, attrs, name, initializers):
    perm = attrs.get("perm")
    return sym.transpose(ins[0].sym,
                         axes=tuple(int(p) for p in perm) if perm else (),
                         name=name)


def _clip(sym, ins, attrs, name, initializers):
    # ONNX: absent bounds mean unbounded (-inf/+inf), not 0
    lo = float(initializers[ins[1].name]) if len(ins) > 1 else \
        float(attrs.get("min", -np.inf))
    hi = float(initializers[ins[2].name]) if len(ins) > 2 else \
        float(attrs.get("max", np.inf))
    return sym.clip(ins[0].sym, a_min=lo, a_max=hi, name=name)


def _leaky(sym, ins, attrs, name, initializers):
    return sym.LeakyReLU(ins[0].sym, act_type="leaky",
                         slope=float(attrs.get("alpha", 0.01)), name=name)


def _prelu(sym, ins, attrs, name, initializers):
    return sym.LeakyReLU(ins[0].sym, ins[1].sym, act_type="prelu",
                         name=name)


def _elu(sym, ins, attrs, name, initializers):
    return sym.LeakyReLU(ins[0].sym, act_type="elu",
                         slope=float(attrs.get("alpha", 1.0)), name=name)


def _gelu(sym, ins, attrs, name, initializers):
    return sym.LeakyReLU(ins[0].sym, act_type="gelu", name=name)


def _identity(sym, ins, attrs, name, initializers):
    return sym.identity(ins[0].sym, name=name)


def _gather(sym, ins, attrs, name, initializers):
    # Embedding pattern: Gather(weight, int_indices)
    w = initializers.get(ins[0].name)
    if w is None:
        raise NotImplementedError("Gather with dynamic data")
    return sym.Embedding(ins[1].sym, ins[0].sym, input_dim=int(w.shape[0]),
                         output_dim=int(w.shape[1]), name=name)


def _cast(sym, ins, attrs, name, initializers):
    onnx2np = {P.DT_FLOAT: "float32", P.DT_INT32: "int32",
               P.DT_INT64: "int64", P.DT_FLOAT16: "float16",
               P.DT_DOUBLE: "float64", P.DT_BOOL: "bool",
               P.DT_UINT8: "uint8", P.DT_INT8: "int8"}
    return sym.Cast(ins[0].sym, dtype=onnx2np[int(attrs["to"])], name=name)


def _reduce_mean(sym, ins, attrs, name, initializers):
    axes = attrs.get("axes")
    return sym.mean(ins[0].sym,
                    axis=tuple(int(a) for a in axes) if axes else None,
                    keepdims=bool(attrs.get("keepdims", 1)), name=name)


_TABLE = {
    "Conv": _conv,
    "ConvTranspose": _deconv,
    "BatchNormalization": _bn,
    "Gemm": _gemm,
    "MatMul": _binop("dot"),
    "MaxPool": _pool("max", False),
    "AveragePool": _pool("avg", False),
    "GlobalMaxPool": _pool("max", True),
    "GlobalAveragePool": _pool("avg", True),
    "Relu": _act("relu"),
    "Sigmoid": _act("sigmoid"),
    "Tanh": _act("tanh"),
    "Softplus": _act("softrelu"),
    "Softsign": _act("softsign"),
    "LeakyRelu": _leaky,
    "PRelu": _prelu,
    "Elu": _elu,
    "Gelu": _gelu,
    "Add": _binop("broadcast_add"),
    "Sub": _binop("broadcast_sub"),
    "Mul": _binop("broadcast_mul"),
    "Div": _binop("broadcast_div"),
    "Flatten": _flatten,
    "Concat": _concat,
    "Softmax": _softmax,
    "Dropout": _dropout,
    "Reshape": _reshape,
    "Transpose": _transpose,
    "Clip": _clip,
    "Identity": _identity,
    "Gather": _gather,
    "Cast": _cast,
    "ReduceMean": _reduce_mean,
    "Exp": lambda sym, ins, a, n, i: sym.exp(ins[0].sym, name=n),
    "Log": lambda sym, ins, a, n, i: sym.log(ins[0].sym, name=n),
    "Sqrt": lambda sym, ins, a, n, i: sym.sqrt(ins[0].sym, name=n),
}


class _Val:
    __slots__ = ("name", "sym")

    def __init__(self, name, sym):
        self.name = name
        self.sym = sym


def import_graph(model):
    """ModelProto -> (sym, arg_params, aux_params)
    (ref: onnx2mx/import_onnx.py GraphProto.from_onnx)."""
    import mxnet_tpu as mx

    g = model.graph
    initializers = {t.name: P.tensor_to_numpy(t)
                    for t in g.initializers}
    vals = {}
    # graph inputs that are not initializers are data
    for vi in g.inputs:
        if vi.name not in initializers:
            vals[vi.name] = _Val(vi.name, mx.sym.var(vi.name))
    for name in initializers:
        vals[name] = _Val(name, mx.sym.var(name))

    for node in g.nodes:
        fn = _TABLE.get(node.op_type)
        if fn is None:
            raise NotImplementedError(
                "ONNX import: no translation for %r (ref: onnx2mx/"
                "_op_translations.py)" % node.op_type)
        ins = [vals[i] for i in node.inputs if i]
        name = node.name or node.outputs[0]
        out = fn(mx.sym, ins, node.attrs, name, initializers)
        for i, oname in enumerate(node.outputs):
            vals[oname] = _Val(oname, out[i] if len(node.outputs) > 1
                               else out)

    outs = [vals[vi.name].sym for vi in g.outputs]
    sym = outs[0] if len(outs) == 1 else mx.sym.Group(outs)

    # split params by the symbol's own arg/aux classification; the
    # imported graph's variable names are the initializer names
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {}
    aux_params = {}
    for name, arr in initializers.items():
        nd_arr = mx.nd.array(arr)
        if name in aux_names:
            aux_params[name] = nd_arr
        elif name in arg_names:
            arg_params[name] = nd_arr
    return sym, arg_params, aux_params
