"""ONNX import/export (ref: python/mxnet/contrib/onnx/__init__.py).

The reference's ONNX bridge requires the external ``onnx`` package at
call time, as does this one; this environment does not ship it, so the
entry points raise the same guided ImportError the reference raises
(ref: contrib/onnx/onnx2mx/import_model.py:30 'Onnx and protobuf need to
be installed')."""
from __future__ import annotations

__all__ = ["import_model", "export_model", "get_model_metadata"]

_MSG = ("Onnx and protobuf need to be installed. Instructions to install "
        "- https://github.com/onnx/onnx")


def _require_onnx():
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ImportError(_MSG)


def import_model(model_file):
    """ref: contrib/onnx/onnx2mx/import_model.py import_model."""
    _require_onnx()
    raise NotImplementedError(
        "ONNX graph import is planned once the onnx package is available "
        "in this environment")


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """ref: contrib/onnx/mx2onnx/export_model.py export_model."""
    _require_onnx()
    raise NotImplementedError(
        "ONNX graph export is planned once the onnx package is available "
        "in this environment")


def get_model_metadata(model_file):
    _require_onnx()
    raise NotImplementedError
