"""ONNX import/export (ref: python/mxnet/contrib/onnx/__init__.py).

Unlike the reference, which requires the external ``onnx`` pip package,
this bridge carries its own minimal protobuf wire codec (proto.py) —
ONNX files are plain protobuf, so (de)serialization needs no
dependency. Translation tables live in mx2onnx.py / onnx2mx.py and
mirror the reference's _op_translations.py coverage for the common op
surface.

API matches the reference:
- export_model(sym, params, input_shape, ...) -> onnx file path
- import_model(model_file) -> (sym, arg_params, aux_params)
- get_model_metadata(model_file) -> {input_tensor_data, output_tensor_data}
"""
from __future__ import annotations

__all__ = ["import_model", "export_model", "get_model_metadata",
           "import_to_gluon"]


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False, opset=13):
    """Export a Symbol (or traceable HybridBlock) + params to ONNX
    (ref: contrib/onnx/mx2onnx/export_model.py export_model)."""
    import numpy as np
    from ...ndarray import NDArray
    from .mx2onnx import export_symbol

    np_params = {}
    for k, v in params.items():
        # reference accepts "arg:name"/"aux:name" prefixed dicts too
        name = k.split(":", 1)[1] if ":" in k else k
        np_params[name] = np.asarray(v.asnumpy() if isinstance(v, NDArray)
                                     else v)
    model = export_symbol(sym, np_params, input_shape, opset=opset)
    with open(onnx_file_path, "wb") as f:
        f.write(model.encode())
    if verbose:
        print("Exported ONNX model to %s (%d nodes)" %
              (onnx_file_path, len(model.graph.nodes)))
    return onnx_file_path


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params)
    (ref: contrib/onnx/onnx2mx/import_model.py import_model)."""
    from .proto import decode_model
    from .onnx2mx import import_graph

    with open(model_file, "rb") as f:
        model = decode_model(f.read())
    return import_graph(model)


def get_model_metadata(model_file):
    """ref: onnx2mx/import_model.py get_model_metadata."""
    from .proto import decode_model

    with open(model_file, "rb") as f:
        model = decode_model(f.read())
    g = model.graph
    init = {t.name for t in g.initializers}
    return {
        "input_tensor_data": [(vi.name, tuple(vi.shape))
                              for vi in g.inputs if vi.name not in init],
        "output_tensor_data": [(vi.name, tuple(vi.shape))
                               for vi in g.outputs],
    }


def import_to_gluon(model_file, ctx=None):
    """ONNX file -> gluon SymbolBlock
    (ref: contrib/onnx/onnx2mx/import_to_gluon.py)."""
    import mxnet_tpu as mx
    from .proto import decode_model
    from .onnx2mx import import_graph

    with open(model_file, "rb") as f:
        model = decode_model(f.read())
    sym, arg_params, aux_params = import_graph(model)
    init = {t.name for t in model.graph.initializers}
    data_names = [vi.name for vi in model.graph.inputs
                  if vi.name not in init]
    inputs = [mx.sym.var(n) for n in data_names]
    net = mx.gluon.SymbolBlock(sym, inputs)
    net_params = net.collect_params()
    for name, arr in {**arg_params, **aux_params}.items():
        if name in net_params:
            net_params[name].set_data(arr)
    return net
