"""Symbol graph -> ONNX ModelProto export.

ref: python/mxnet/contrib/onnx/mx2onnx/_op_translations.py (the
reference's ~2000-line translation table) and export_model.py. This
covers the op surface the model zoo + common Gluon nets produce:
Convolution, BatchNorm, FullyConnected, Activation, Pooling, Flatten,
Concat, Dropout, softmax/SoftmaxOutput, elemwise/broadcast arithmetic,
Reshape, transpose, clip, LeakyReLU, mean/ReduceMean, Deconvolution,
InstanceNorm, LayerNorm, embedding, slicing and Identity aliases.
"""
from __future__ import annotations

import numpy as np

from . import proto as P

__all__ = ["export_symbol"]


def _pair(v):
    return [int(v[0]), int(v[1])] if isinstance(v, (tuple, list)) \
        else [int(v), int(v)]


class _Ctx:
    def __init__(self, params):
        self.graph = P.GraphProto()
        self.params = params      # name -> np array (initializers)
        self.used_params = set()

    def init_tensor(self, name, arr):
        self.graph.initializers.append(P.tensor_from_numpy(name, arr))

    def add(self, op_type, inputs, outputs, name, **attrs):
        self.graph.nodes.append(
            P.NodeProto(op_type, name=name, inputs=inputs,
                        outputs=outputs, attrs=attrs))


def _conv(ctx, n, ins, out):
    a = n.attrs
    attrs = dict(kernel_shape=_pair(a["kernel"]),
                 strides=_pair(a.get("stride", (1, 1))),
                 dilations=_pair(a.get("dilate", (1, 1))),
                 group=int(a.get("num_group", 1)))
    p = _pair(a.get("pad", (0, 0)))
    attrs["pads"] = [p[0], p[1], p[0], p[1]]
    ctx.add("Conv", ins, [out], n.name, **attrs)


def _deconv(ctx, n, ins, out):
    a = n.attrs
    attrs = dict(kernel_shape=_pair(a["kernel"]),
                 strides=_pair(a.get("stride", (1, 1))),
                 dilations=_pair(a.get("dilate", (1, 1))),
                 group=int(a.get("num_group", 1)))
    p = _pair(a.get("pad", (0, 0)))
    attrs["pads"] = [p[0], p[1], p[0], p[1]]
    ctx.add("ConvTranspose", ins, [out], n.name, **attrs)


def _batchnorm(ctx, n, ins, out):
    a = n.attrs
    # defaults must match the op registration (ops/nn.py batch_norm:
    # eps=1e-3, fix_gamma=True — the reference's BatchNorm defaults too)
    if a.get("fix_gamma", True):
        # reference bakes fixed gamma to ones at export
        gname = ins[1]
        if gname in ctx.params:
            ctx.params[gname] = np.ones_like(ctx.params[gname])
    ctx.add("BatchNormalization", ins, [out], n.name,
            epsilon=float(a.get("eps", 1e-3)),
            momentum=float(a.get("momentum", 0.9)))


def _fc(ctx, n, ins, out):
    a = n.attrs
    data = ins[0]
    if a.get("flatten", True):
        flat = n.name + "_flatten"
        ctx.add("Flatten", [data], [flat], flat, axis=1)
        data = flat
    if a.get("no_bias", False):
        # Gemm requires C; synthesize a zero bias like the reference
        bias = n.name + "_zero_bias"
        ctx.init_tensor(bias, np.zeros((int(a["num_hidden"]),), np.float32))
        gemm_in = [data, ins[1], bias]
    else:
        gemm_in = [data, ins[1], ins[2]]
    ctx.add("Gemm", gemm_in, [out], n.name, alpha=1.0, beta=1.0,
            transA=0, transB=1)


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _activation(ctx, n, ins, out):
    ctx.add(_ACT[n.attrs.get("act_type", "relu")], ins, [out], n.name)


def _pooling(ctx, n, ins, out):
    a = n.attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool", False):
        ctx.add("GlobalMaxPool" if ptype == "max" else "GlobalAveragePool",
                ins, [out], n.name)
        return
    attrs = dict(kernel_shape=_pair(a["kernel"]),
                 strides=_pair(a.get("stride", (1, 1))))
    p = _pair(a.get("pad", (0, 0)))
    attrs["pads"] = [p[0], p[1], p[0], p[1]]
    if a.get("pooling_convention", "valid") == "full":
        attrs["ceil_mode"] = 1
    if ptype == "avg":
        attrs["count_include_pad"] = \
            1 if a.get("count_include_pad", True) else 0
    ctx.add("MaxPool" if ptype == "max" else "AveragePool",
            ins, [out], n.name, **attrs)


def _softmax(ctx, n, ins, out):
    ctx.add("Softmax", ins[:1], [out], n.name,
            axis=int(n.attrs.get("axis", -1)))


def _dropout(ctx, n, ins, out):
    ctx.add("Dropout", ins, [out], n.name)


def _flatten(ctx, n, ins, out):
    ctx.add("Flatten", ins, [out], n.name, axis=1)


def _concat(ctx, n, ins, out):
    ctx.add("Concat", ins, [out], n.name,
            axis=int(n.attrs.get("dim", n.attrs.get("axis", 1))))


def _reshape(ctx, n, ins, out):
    shape = [int(s) for s in n.attrs.get("shape", ())]
    if any(s in (-2, -3, -4) for s in shape):
        # MXNet's special codes (copy-rest / merge / split) have no ONNX
        # Reshape equivalent (ONNX defines only 0 and -1)
        raise NotImplementedError(
            "ONNX export: Reshape special shape codes -2/-3/-4 are not "
            "representable in ONNX (got %r)" % (shape,))
    sname = n.name + "_shape"
    ctx.init_tensor(sname, np.asarray(shape, np.int64))
    ctx.add("Reshape", [ins[0], sname], [out], n.name)


def _transpose(ctx, n, ins, out):
    axes = n.attrs.get("axes", ())
    attrs = {"perm": [int(x) for x in axes]} if axes else {}
    ctx.add("Transpose", ins, [out], n.name, **attrs)


def _clip(ctx, n, ins, out):
    lo = n.name + "_min"
    hi = n.name + "_max"
    ctx.init_tensor(lo, np.asarray(float(n.attrs.get("a_min", 0)),
                                   np.float32))
    ctx.init_tensor(hi, np.asarray(float(n.attrs.get("a_max", 0)),
                                   np.float32))
    ctx.add("Clip", [ins[0], lo, hi], [out], n.name)


def _leaky(ctx, n, ins, out):
    act = n.attrs.get("act_type", "leaky")
    if act in ("leaky", "prelu"):
        if act == "prelu":
            ctx.add("PRelu", ins, [out], n.name)
        else:
            ctx.add("LeakyRelu", ins[:1], [out], n.name,
                    alpha=float(n.attrs.get("slope", 0.25)))
    elif act == "elu":
        ctx.add("Elu", ins[:1], [out], n.name,
                alpha=float(n.attrs.get("slope", 0.25)))
    elif act == "gelu":
        ctx.add("Gelu", ins[:1], [out], n.name)
    else:
        raise ValueError("LeakyReLU act_type %r not exportable" % act)


def _mean(ctx, n, ins, out):
    axis = n.attrs.get("axis", None)
    attrs = {"keepdims": 1 if n.attrs.get("keepdims", False) else 0}
    if axis is not None:
        attrs["axes"] = [int(a) for a in (
            axis if isinstance(axis, (tuple, list)) else (axis,))]
    ctx.add("ReduceMean", ins, [out], n.name, **attrs)


def _binop(onnx_op):
    def f(ctx, n, ins, out):
        ctx.add(onnx_op, ins, [out], n.name)
    return f


def _embedding(ctx, n, ins, out):
    # Gather(weight, indices)
    cast = n.name + "_idx64"
    ctx.add("Cast", [ins[0]], [cast], cast, to=P.DT_INT64)
    ctx.add("Gather", [ins[1], cast], [out], n.name)


def _layernorm(ctx, n, ins, out):
    ctx.add("LayerNormalization", ins, [out], n.name,
            epsilon=float(n.attrs.get("eps", 1e-5)),
            axis=int(n.attrs.get("axis", -1)))


_TABLE = {
    "Convolution": _conv,
    "Deconvolution": _deconv,
    "BatchNorm": _batchnorm,
    "FullyConnected": _fc,
    "Activation": _activation,
    "Pooling": _pooling,
    "softmax": _softmax,
    "Softmax": _softmax,
    "SoftmaxOutput": _softmax,
    "SoftmaxActivation": _softmax,
    "Dropout": _dropout,
    "Flatten": _flatten,
    "flatten": _flatten,
    "Concat": _concat,
    "concat": _concat,
    "Reshape": _reshape,
    "reshape": _reshape,
    "transpose": _transpose,
    "clip": _clip,
    "LeakyReLU": _leaky,
    "mean": _mean,
    "Embedding": _embedding,
    "LayerNorm": _layernorm,
    "add": _binop("Add"),
    "elemwise_add": _binop("Add"),
    "broadcast_add": _binop("Add"),
    "_plus": _binop("Add"),
    "subtract": _binop("Sub"),
    "elemwise_sub": _binop("Sub"),
    "broadcast_sub": _binop("Sub"),
    "multiply": _binop("Mul"),
    "elemwise_mul": _binop("Mul"),
    "broadcast_mul": _binop("Mul"),
    "divide": _binop("Div"),
    "elemwise_div": _binop("Div"),
    "broadcast_div": _binop("Div"),
    "dot": _binop("MatMul"),
    "identity": _binop("Identity"),
    "relu": lambda ctx, n, ins, out: ctx.add("Relu", ins, [out], n.name),
    "sigmoid": lambda ctx, n, ins, out: ctx.add("Sigmoid", ins, [out],
                                                n.name),
    "tanh": lambda ctx, n, ins, out: ctx.add("Tanh", ins, [out], n.name),
    "exp": _binop("Exp"),
    "log": _binop("Log"),
    "sqrt": _binop("Sqrt"),
}


def export_symbol(sym, params, input_shape, input_dtype="float32",
                  opset=13):
    """Translate a Symbol + params into an ONNX ModelProto.

    params: dict name -> numpy array (args + aux merged, like the
    reference's export_model params argument)."""
    nodes = sym._topo()
    params = {k: np.asarray(v) for k, v in params.items()}
    ctx = _Ctx(params)

    # output name per (node, out_idx)
    names = {}
    data_inputs = []
    for n in nodes:
        if n.is_variable():
            names[(id(n), 0)] = n.name
            if n.name not in params:
                data_inputs.append(n.name)
        else:
            for i in range(max(1, n.num_outputs)):
                names[(id(n), i)] = n.name if i == 0 \
                    else "%s_out%d" % (n.name, i)

    for n in nodes:
        if n.is_variable():
            continue
        fn = _TABLE.get(n.op)
        if fn is None:
            raise NotImplementedError(
                "ONNX export: no translation for op %r (ref: mx2onnx/"
                "_op_translations.py)" % n.op)
        ins = [names[(id(src), oi)] for src, oi in n.inputs]
        fn(ctx, n, ins, names[(id(n), 0)])

    # initializers for used params
    graph_input_names = set()
    for node in ctx.graph.nodes:
        graph_input_names.update(node.inputs)
    existing = {t.name for t in ctx.graph.initializers}
    for name, arr in ctx.params.items():
        if name in graph_input_names and name not in existing:
            ctx.graph.initializers.append(P.tensor_from_numpy(name, arr))

    shapes = input_shape if isinstance(input_shape, list) \
        else [input_shape]
    in_dt = P._NP2ONNX.get(np.dtype(input_dtype), P.DT_FLOAT)
    for dname, shp in zip(data_inputs, shapes):
        ctx.graph.inputs.append(P.ValueInfo(dname, in_dt, list(shp)))
    for node, oi in sym._outputs:
        ctx.graph.outputs.append(
            P.ValueInfo(names[(id(node), oi)], P.DT_FLOAT, []))
    return P.ModelProto(graph=ctx.graph, opset=opset)
