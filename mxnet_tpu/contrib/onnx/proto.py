"""Minimal protobuf wire-format codec for the ONNX message subset.

The reference's ONNX bridge depends on the `onnx` pip package purely for
(de)serializing ModelProto (ref: python/mxnet/contrib/onnx/onnx2mx/
import_model.py:30). ONNX files are plain protobuf, and protobuf's wire
format is simple varint/length-delimited framing — so this module
implements exactly the fields the bridge needs, with no dependency.

Field numbers follow onnx/onnx.proto3 (ONNX IR v4+, opset-independent):
ModelProto{1:ir_version, 2:producer_name, 3:producer_version, 7:graph,
8:opset_import}; GraphProto{1:node, 2:name, 5:initializer, 11:input,
12:output, 13:value_info}; NodeProto{1:input, 2:output, 3:name,
4:op_type, 5:attribute}; AttributeProto{1:name, 2:f, 3:i, 4:s, 5:t,
7:floats, 8:ints, 9:strings, 20:type}; TensorProto{1:dims, 2:data_type,
4:float_data, 7:int64_data, 8:name, 9:raw_data};
ValueInfoProto{1:name, 2:type}; TypeProto{1:tensor_type{1:elem_type,
2:shape{1:dim{1:dim_value, 2:dim_param}}}};
OperatorSetIdProto{1:domain, 2:version}.
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = ["TensorProto", "AttributeProto", "NodeProto", "GraphProto",
           "ModelProto", "ValueInfo", "encode_model", "decode_model",
           "tensor_from_numpy", "tensor_to_numpy"]

# ONNX TensorProto.DataType
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64 = 1, 2, 3, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE = 9, 10, 11

_NP2ONNX = {np.dtype("float32"): DT_FLOAT, np.dtype("uint8"): DT_UINT8,
            np.dtype("int8"): DT_INT8, np.dtype("int32"): DT_INT32,
            np.dtype("int64"): DT_INT64, np.dtype("bool"): DT_BOOL,
            np.dtype("float16"): DT_FLOAT16, np.dtype("float64"): DT_DOUBLE}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

def _w_varint(out, v):
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_tag(out, field, wire):
    _w_varint(out, (field << 3) | wire)


def _w_len(out, field, payload):
    _w_tag(out, field, 2)
    _w_varint(out, len(payload))
    out.extend(payload)


def _w_int(out, field, v):
    _w_tag(out, field, 0)
    _w_varint(out, int(v))


def _w_float(out, field, v):
    _w_tag(out, field, 5)
    out.extend(struct.pack("<f", float(v)))


def _w_str(out, field, s):
    _w_len(out, field, s.encode() if isinstance(s, str) else s)


def _r_varint(buf, pos):
    shift = 0
    val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return val, pos


def _signed(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def _scan(buf):
    """Parse one message level into {field: [(wire, value), ...]}."""
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _r_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _r_varint(buf, pos)
        elif wire == 2:
            ln, pos = _r_varint(buf, pos)
            v = bytes(buf[pos:pos + ln])
            pos += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        fields.setdefault(field, []).append((wire, v))
    return fields


def _one(fields, num, default=None):
    vs = fields.get(num)
    return vs[-1][1] if vs else default


def _many(fields, num):
    return [v for _, v in fields.get(num, ())]


def _packed_ints(fields, num):
    out = []
    for wire, v in fields.get(num, ()):
        if wire == 0:
            out.append(_signed(v))
        else:  # packed
            pos = 0
            while pos < len(v):
                x, pos = _r_varint(v, pos)
                out.append(_signed(x))
    return out


def _packed_floats(fields, num):
    out = []
    for wire, v in fields.get(num, ()):
        if wire == 5:
            out.append(v)
        else:
            out.extend(struct.unpack("<%df" % (len(v) // 4), v))
    return out


# ---------------------------------------------------------------------------
# message classes (plain data holders)
# ---------------------------------------------------------------------------

class TensorProto:
    def __init__(self, name="", dims=(), data_type=DT_FLOAT, raw=b""):
        self.name = name
        self.dims = list(dims)
        self.data_type = data_type
        self.raw = raw

    def encode(self):
        out = bytearray()
        for d in self.dims:
            _w_int(out, 1, d)
        _w_int(out, 2, self.data_type)
        _w_str(out, 8, self.name)
        _w_len(out, 9, self.raw)
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        t = cls(name=_one(f, 8, b"").decode(),
                dims=_packed_ints(f, 1),
                data_type=_one(f, 2, DT_FLOAT))
        t.raw = _one(f, 9, b"")
        if not t.raw:
            # fall back to typed repeated fields
            fd = _packed_floats(f, 4)
            if fd:
                t.raw = np.asarray(fd, np.float32).tobytes()
            else:
                i64 = _packed_ints(f, 7)
                if i64:
                    t.raw = np.asarray(i64, np.int64).tobytes()
                else:
                    i32 = _packed_ints(f, 5)
                    if i32:
                        if t.data_type == DT_FLOAT16:
                            # spec stores fp16 as raw uint16 bit patterns
                            # inside int32_data, not numeric values
                            t.raw = np.asarray(i32, np.uint16) \
                                .view(np.float16).tobytes()
                        else:
                            dt = _ONNX2NP.get(t.data_type,
                                              np.dtype("int32"))
                            t.raw = np.asarray(i32, dt).tobytes()
        return t


def tensor_from_numpy(name, arr):
    arr = np.ascontiguousarray(arr)
    return TensorProto(name=name, dims=arr.shape,
                       data_type=_NP2ONNX[arr.dtype], raw=arr.tobytes())


def tensor_to_numpy(t):
    dt = _ONNX2NP.get(t.data_type)
    if dt is None:
        raise ValueError("unsupported ONNX tensor dtype %d" % t.data_type)
    return np.frombuffer(t.raw, dt).reshape(t.dims).copy()


class AttributeProto:
    def __init__(self, name, value):
        self.name = name
        self.value = value

    def encode(self):
        out = bytearray()
        _w_str(out, 1, self.name)
        v = self.value
        if isinstance(v, float):
            _w_float(out, 2, v)
            _w_int(out, 20, AT_FLOAT)
        elif isinstance(v, bool) or isinstance(v, int):
            _w_int(out, 3, int(v))
            _w_int(out, 20, AT_INT)
        elif isinstance(v, str):
            _w_str(out, 4, v)
            _w_int(out, 20, AT_STRING)
        elif isinstance(v, TensorProto):
            _w_len(out, 5, v.encode())
            _w_int(out, 20, AT_TENSOR)
        elif isinstance(v, (list, tuple)):
            if v and isinstance(v[0], float):
                for x in v:
                    _w_float(out, 7, x)
                _w_int(out, 20, AT_FLOATS)
            elif v and isinstance(v[0], str):
                for x in v:
                    _w_str(out, 9, x)
                _w_int(out, 20, AT_STRINGS)
            else:
                for x in v:
                    _w_int(out, 8, int(x))
                _w_int(out, 20, AT_INTS)
        else:
            raise TypeError("unsupported attribute %r=%r" % (self.name, v))
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        name = _one(f, 1, b"").decode()
        at = _one(f, 20, 0)
        if at == AT_FLOAT or (at == 0 and 2 in f):
            return cls(name, _one(f, 2))
        if at == AT_INT or (at == 0 and 3 in f):
            return cls(name, _signed(_one(f, 3)))
        if at == AT_STRING or (at == 0 and 4 in f):
            return cls(name, _one(f, 4, b"").decode())
        if at == AT_TENSOR or (at == 0 and 5 in f):
            return cls(name, TensorProto.decode(_one(f, 5)))
        if at == AT_FLOATS or (at == 0 and 7 in f):
            return cls(name, _packed_floats(f, 7))
        if at == AT_INTS or (at == 0 and 8 in f):
            return cls(name, _packed_ints(f, 8))
        if at == AT_STRINGS or (at == 0 and 9 in f):
            return cls(name, [s.decode() for s in _many(f, 9)])
        return cls(name, None)


class NodeProto:
    def __init__(self, op_type, name="", inputs=(), outputs=(), attrs=None):
        self.op_type = op_type
        self.name = name
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.attrs = dict(attrs or {})

    def encode(self):
        out = bytearray()
        for i in self.inputs:
            _w_str(out, 1, i)
        for o in self.outputs:
            _w_str(out, 2, o)
        _w_str(out, 3, self.name)
        _w_str(out, 4, self.op_type)
        for k, v in self.attrs.items():
            _w_len(out, 5, AttributeProto(k, v).encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        attrs = {}
        for a in _many(f, 5):
            ap = AttributeProto.decode(a)
            attrs[ap.name] = ap.value
        return cls(op_type=_one(f, 4, b"").decode(),
                   name=_one(f, 3, b"").decode(),
                   inputs=[s.decode() for s in _many(f, 1)],
                   outputs=[s.decode() for s in _many(f, 2)],
                   attrs=attrs)


class ValueInfo:
    def __init__(self, name, elem_type=DT_FLOAT, shape=()):
        self.name = name
        self.elem_type = elem_type
        self.shape = list(shape)   # ints or strings (dim_param)

    def encode(self):
        shp = bytearray()
        for d in self.shape:
            dim = bytearray()
            if isinstance(d, str):
                _w_str(dim, 2, d)
            else:
                _w_int(dim, 1, d)
            _w_len(shp, 1, dim)
        tt = bytearray()
        _w_int(tt, 1, self.elem_type)
        _w_len(tt, 2, shp)
        tp = bytearray()
        _w_len(tp, 1, tt)
        out = bytearray()
        _w_str(out, 1, self.name)
        _w_len(out, 2, tp)
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        name = _one(f, 1, b"").decode()
        elem, shape = DT_FLOAT, []
        tp = _one(f, 2)
        if tp:
            tf = _scan(tp)
            tt = _one(tf, 1)
            if tt:
                ttf = _scan(tt)
                elem = _one(ttf, 1, DT_FLOAT)
                shp = _one(ttf, 2)
                if shp:
                    for dim in _many(_scan(shp), 1):
                        df = _scan(dim)
                        if 1 in df:
                            shape.append(_signed(_one(df, 1)))
                        else:
                            shape.append(_one(df, 2, b"").decode())
        return cls(name, elem, shape)


class GraphProto:
    def __init__(self, name="graph"):
        self.name = name
        self.nodes = []
        self.initializers = []
        self.inputs = []     # ValueInfo
        self.outputs = []    # ValueInfo

    def encode(self):
        out = bytearray()
        for n in self.nodes:
            _w_len(out, 1, n.encode())
        _w_str(out, 2, self.name)
        for t in self.initializers:
            _w_len(out, 5, t.encode())
        for vi in self.inputs:
            _w_len(out, 11, vi.encode())
        for vi in self.outputs:
            _w_len(out, 12, vi.encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        g = cls(name=_one(f, 2, b"graph").decode())
        g.nodes = [NodeProto.decode(b) for b in _many(f, 1)]
        g.initializers = [TensorProto.decode(b) for b in _many(f, 5)]
        g.inputs = [ValueInfo.decode(b) for b in _many(f, 11)]
        g.outputs = [ValueInfo.decode(b) for b in _many(f, 12)]
        return g


class ModelProto:
    def __init__(self, graph=None, ir_version=7, opset=13,
                 producer="mxnet_tpu"):
        self.graph = graph
        self.ir_version = ir_version
        self.opset = opset
        self.producer = producer

    def encode(self):
        out = bytearray()
        _w_int(out, 1, self.ir_version)
        _w_str(out, 2, self.producer)
        _w_len(out, 7, self.graph.encode())
        ops = bytearray()
        _w_str(ops, 1, "")
        _w_int(ops, 2, self.opset)
        _w_len(out, 8, ops)
        return bytes(out)

    @classmethod
    def decode(cls, buf):
        f = _scan(buf)
        m = cls(ir_version=_one(f, 1, 7),
                producer=_one(f, 2, b"").decode())
        ops = _one(f, 8)
        if ops:
            m.opset = _one(_scan(ops), 2, 13)
        g = _one(f, 7)
        m.graph = GraphProto.decode(g) if g else None
        return m


def encode_model(model):
    return model.encode()


def decode_model(data):
    return ModelProto.decode(data)
