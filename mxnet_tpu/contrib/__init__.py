"""Experimental / contributed subsystems
(ref: python/mxnet/contrib/__init__.py): AMP, INT8 quantization, ONNX."""
from . import amp  # noqa: F401


_LAZY_SUBMODULES = ("autograd", "io", "ndarray", "symbol", "tensorboard")


def __getattr__(name):
    # autograd/io/ndarray/symbol shims re-export frontend namespaces that
    # themselves import contrib ops — lazy to break the import cycle
    # (ref: python/mxnet/contrib/__init__.py imports these eagerly; its
    # C-registry has no such cycle). `quant` aliases quantization
    # (ref: contrib/__init__.py `from . import quantization as quant`).
    if name == "quant":
        from . import quantization
        globals()["quant"] = quantization
        return quantization
    if name in _LAZY_SUBMODULES:
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


def __dir__():
    return sorted(set(globals()) | set(_LAZY_SUBMODULES) | {"quant"})
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import text  # noqa: F401
