"""``mx.contrib.ndarray`` namespace re-export
(ref: python/mxnet/contrib/ndarray.py — there it is generated from the
contrib op registry; here it delegates to nd.contrib, whose surface is
partly dynamic)."""
from ..ndarray import contrib as _nd_contrib
from ..ndarray.contrib import *  # noqa: F401,F403


def __getattr__(name):
    return getattr(_nd_contrib, name)


def __dir__():
    return dir(_nd_contrib)
