"""``mx.contrib.symbol`` namespace re-export
(ref: python/mxnet/contrib/symbol.py — generated from the contrib op
registry there; delegates to sym.contrib here)."""
from ..symbol import contrib as _sym_contrib
from ..symbol.contrib import *  # noqa: F401,F403


def __getattr__(name):
    return getattr(_sym_contrib, name)


def __dir__():
    return dir(_sym_contrib)
