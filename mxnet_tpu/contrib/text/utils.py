"""Text tokenization helpers (ref: python/mxnet/contrib/text/utils.py)."""
from __future__ import annotations

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (ref: utils.py count_tokens_from_str)."""
    source_str = re.sub(r"\n+", " ", source_str) if seq_delim == "\n" \
        else source_str.replace(seq_delim, " ")
    if to_lower:
        source_str = source_str.lower()
    tokens = [t for t in source_str.split(token_delim) if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter
