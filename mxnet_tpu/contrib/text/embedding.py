"""Token embeddings (ref: python/mxnet/contrib/text/embedding.py).

The reference downloads pretrained GloVe/fastText tables; this
environment has no egress, so pretrained classes load from local files in
the same text format ('token v1 v2 ... vN' per line) via
``from_file`` / ``CustomEmbedding`` — the reference's own custom-embedding
path (embedding.py:CustomEmbedding)."""
from __future__ import annotations

import io
import logging

import numpy as _np

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "CompositeEmbedding"]

_REGISTRY = {}  # mxlint: disable=MX003 (populated by @register decorators at import time, single-threaded; read-only afterwards)


def register(klass):
    """ref: embedding.py register."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(embedding_name, **kwargs):
    """ref: embedding.py create."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError("Cannot find embedding %r; registered: %s"
                       % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """ref: embedding.py get_pretrained_file_names. No pretrained archives
    ship in this environment — load local files via CustomEmbedding."""
    return {name: [] for name in _REGISTRY} if embedding_name is None else []


class TokenEmbedding(Vocabulary):
    """Vocabulary + dense vectors (ref: embedding.py:60 _TokenEmbedding)."""

    def __init__(self, unknown_token="<unk>", init_unknown_vec=None):
        super().__init__(counter=None, unknown_token=unknown_token)
        self._init_unknown_vec = init_unknown_vec or (lambda s: nd.zeros(s))
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_txt(self, file_handle, elem_delim=" "):
        """Parse 'token v1 ... vN' lines (ref: embedding.py
        _load_embedding_txt)."""
        vecs = []
        for lineno, line in enumerate(file_handle):
            parts = line.rstrip().split(elem_delim)
            if len(parts) < 2:
                continue
            if lineno == 0 and len(parts) == 2:
                # fastText .vec header: "<token_count> <dim>" — both
                # numeric, not an embedding row (ref: embedding.py FastText
                # _load_embedding skipping the header)
                try:
                    int(parts[0]), int(parts[1])
                    continue
                except ValueError:
                    pass
            token, elems = parts[0], parts[1:]
            if self._vec_len == 0:
                self._vec_len = len(elems)
                vecs.append(_np.zeros(self._vec_len, "float32"))  # <unk>
            if len(elems) != self._vec_len:
                logging.warning("line %d: expected %d dims, got %d — "
                                "skipped", lineno, self._vec_len, len(elems))
                continue
            if token in self._token_to_idx:
                continue
            self._idx_to_token.append(token)
            self._token_to_idx[token] = len(self._idx_to_token) - 1
            vecs.append(_np.asarray(elems, "float32"))
        assert vecs, "no embedding vectors found"
        mat = _np.stack(vecs)
        unk = self._init_unknown_vec((self._vec_len,))
        mat[0] = unk.asnumpy() if hasattr(unk, "asnumpy") else unk
        self._idx_to_vec = nd.array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """ref: embedding.py get_vecs_by_tokens."""
        single = isinstance(tokens, str)
        seq = [tokens] if single else tokens
        idxs = []
        for t in seq:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idxs.append(0 if i is None else i)
        vecs = self._idx_to_vec[nd.array(_np.asarray(idxs, "int32"))]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """ref: embedding.py update_token_vectors."""
        seq = [tokens] if isinstance(tokens, str) else tokens
        if not isinstance(new_vectors, (list, tuple)):
            new_vectors = [new_vectors[i] for i in range(len(seq))] \
                if len(seq) > 1 else [new_vectors]
        for t, v in zip(seq, new_vectors):
            if t not in self._token_to_idx:
                raise ValueError("token %r is unknown" % t)
            self._idx_to_vec[self._token_to_idx[t]] = v


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a local text file (ref: embedding.py:CustomEmbedding)."""

    def __init__(self, pretrained_file_path=None, elem_delim=" ",
                 encoding="utf8", unknown_token="<unk>",
                 init_unknown_vec=None, **kwargs):
        super().__init__(unknown_token=unknown_token,
                         init_unknown_vec=init_unknown_vec)
        if pretrained_file_path is not None:
            with io.open(pretrained_file_path, "r",
                         encoding=encoding) as f:
                self._load_embedding_txt(f, elem_delim)


@register
class GloVe(CustomEmbedding):
    """GloVe-format loader (ref: embedding.py:GloVe). Pretrained archives
    are not downloadable here; pass pretrained_file_path to a local copy."""


@register
class FastText(CustomEmbedding):
    """fastText-format loader (ref: embedding.py:FastText); first line with
    'count dim' headers is tolerated (skipped by the <2 column check)."""


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (ref: embedding.py:CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        mats = []
        for emb in token_embeddings:
            mats.append(emb.get_vecs_by_tokens(self._idx_to_token).asnumpy())
        mat = _np.concatenate(mats, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = nd.array(mat)
