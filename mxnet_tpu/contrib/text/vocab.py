"""Vocabulary: token <-> index mapping
(ref: python/mxnet/contrib/text/vocab.py:30 Vocabulary)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens by frequency with reserved tokens up front
    (ref: vocab.py:75 __init__). Index 0 is the unknown token."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("`min_freq` must be set to a positive value.")
        if reserved_tokens is not None:
            if unknown_token in reserved_tokens or \
                    len(set(reserved_tokens)) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` cannot contain "
                                 "duplicates or the unknown token.")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) \
            if reserved_tokens else None
        self._idx_to_token = [unknown_token] + (list(reserved_tokens)
                                               if reserved_tokens else [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        # stable order: frequency desc, then insertion (ref: vocab.py:121)
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        limit = len(self._idx_to_token) + (most_freq_count
                                           if most_freq_count is not None
                                           else len(token_freqs))
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) >= limit:
                break
            if token not in self._token_to_idx:
                self._idx_to_token.append(token)
                self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Tokens -> indices; unknown tokens map to index 0
        (ref: vocab.py to_indices)."""
        single = isinstance(tokens, str)
        seq = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in seq]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Indices -> tokens (ref: vocab.py to_tokens)."""
        single = isinstance(indices, int)
        seq = [indices] if single else indices
        out = []
        for i in seq:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("Token index %d out of range" % i)
            out.append(self._idx_to_token[i])
        return out[0] if single else out
