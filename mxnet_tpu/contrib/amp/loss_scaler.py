"""Dynamic loss scaling (ref: python/mxnet/contrib/amp/loss_scaler.py).

Kept for API compatibility and for float16 policies. bfloat16 — the TPU
default — shares float32's exponent range, so overflow-driven rescaling
is a no-op there in practice; the scaler still guards against inf/nan
gradients from divergence."""
from __future__ import annotations


class LossScaler:
    """ref: loss_scaler.py LossScaler — scale up after
    ``scale_window`` clean steps, halve on overflow."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0
        self._min_scale = 1.0

    def has_overflow(self, params):
        """True if any gradient is non-finite (ref: loss_scaler.py
        has_overflow, fused multi_all_finite kernel). One device-side
        reduction over all grads, ONE host sync — not one per parameter."""
        import jax.numpy as jnp
        checks = []
        for p in params:
            if getattr(p, "grad_req", "write") == "null":
                continue  # frozen param: no gradient to check
            g = p.grad() if callable(getattr(p, "grad", None)) else p.grad
            if g is None:
                continue
            checks.append(jnp.isfinite(g._data).all())
        if not checks:
            return False
        return not bool(jnp.stack(checks).all())

    def update_scale(self, overflow):
        """ref: loss_scaler.py update_scale.

        Every call feeds ``metrics()['health']`` — the training-health
        plane is the SINGLE owner of overflow/skip accounting
        (``amp_overflow_skips`` / ``amp_loss_scale``), counted with or
        without profiling (the ``account`` contract)."""
        if overflow:
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped == self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
        from ..._debug import healthmon as _healthmon
        _healthmon.note_amp(overflow, self.loss_scale)
