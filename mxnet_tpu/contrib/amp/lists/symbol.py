"""Op classification for automatic mixed precision
(ref: python/mxnet/contrib/amp/lists/symbol.py FP16_FUNCS / FP32_FUNCS /
FP16_FP32_FUNCS / WIDEST_TYPE_CASTS).

TPU re-design: the target low precision is bfloat16, which shares
float32's exponent range — so the FP32 list only needs ops whose
*accumulation* precision matters (normalizations, softmax-with-reduction,
losses), not the overflow-prone ops the fp16 list guards.
"""

# MXU-bound ops: always cast inputs to the target dtype — these are where
# the FLOPs are, and bf16 doubles MXU throughput
# (ref list: FP16_FUNCS — Convolution, FullyConnected, RNN ...)
TARGET_DTYPE_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "RNN",
    "dot", "batch_dot", "linalg_gemm", "linalg_gemm2",
]

# numerically sensitive ops: force float32 inputs
# (ref list: FP32_FUNCS — softmax outputs, norms, exp/log family, losses)
FP32_OPS = [
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
    "L2Normalization", "LRN", "softmax", "Softmax", "softmin",
    "SoftmaxActivation", "SoftmaxOutput", "softmax_cross_entropy",
    "smooth_l1", "MakeLoss", "exp", "expm1", "log", "log10", "log2",
    "log1p", "log_softmax", "norm", "mean", "sum", "prod", "cumsum",
    "erfinv", "gamma", "gammaln", "CTCLoss", "ctc_loss",
]

# multi-input elementwise ops: cast all inputs to the widest present dtype
# (ref list: WIDEST_TYPE_CASTS)
WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "divide", "broadcast_add",
    "broadcast_sub", "broadcast_mul", "broadcast_div", "maximum",
    "minimum", "broadcast_maximum", "broadcast_minimum", "hypot",
    "concat", "Concat", "stack", "where", "power", "broadcast_power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
]

# everything else runs in whatever dtype its inputs already have
# (ref: FP16_FP32_FUNCS — the "don't care" set)
