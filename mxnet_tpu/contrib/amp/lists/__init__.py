"""AMP op lists (ref: python/mxnet/contrib/amp/lists/symbol.py)."""
from . import symbol  # noqa: F401
