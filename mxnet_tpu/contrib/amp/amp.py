"""Automatic mixed precision (ref: python/mxnet/contrib/amp/amp.py).

TPU-native re-design. The reference rewrites every generated op wrapper at
``amp.init()`` to insert ``amp_cast`` nodes (amp.py:251) because fp16 on
GPUs needs careful overflow management. On TPU the target dtype is
**bfloat16** — same exponent range as float32, natively consumed by the
MXU at 2x throughput — so the policy is simpler and is applied at the one
dispatch choke point (``ndarray.register.invoke``) instead of rewriting
namespaces:

- MXU-bound ops (matmul/conv/rnn) get inputs cast to the target dtype;
- accumulation-sensitive ops (norms, softmax+reduce, losses) get float32;
- multi-input elementwise ops are cast to the widest input dtype;
- everything else passes through.

The dynamic ``LossScaler`` + overflow-skip step survive for API compat and
for ``float16`` targets.
"""
from __future__ import annotations

import contextlib
import logging
import warnings

import numpy as _np

from ...base import canonical_dtype
from ...ndarray import register as _register
from ...ndarray.ndarray import NDArray
from .loss_scaler import LossScaler
from .lists import symbol as _lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "list_lp16_ops",
           "list_fp32_ops", "list_widest_type_cast"]

_amp_initialized = False
_target_dtype = None
_NORM_PARAM_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                        "moving_mean", "moving_var")


def _is_float(dt):
    return _np.issubdtype(_np.dtype(dt), _np.floating) or \
        str(dt) == "bfloat16"


def _cast_nd(x, dtype):
    if isinstance(x, NDArray) and _is_float(x.dtype) and \
            str(x.dtype) != str(dtype):
        return x.astype(dtype)
    return x


# active op classification (set by init, cleared by _reset) — the canonical
# lists in lists/symbol.py are never mutated, so init/_reset cycles with
# custom op lists can't leak state between them
_active_lists = None


def _make_hook(target, fp32, widest, target_dtype):

    def hook(op_name, args, kwargs):
        if op_name in target:
            dt = target_dtype
        elif op_name in fp32:
            dt = "float32"
        elif op_name in widest:
            dts = [a.dtype for a in list(args) + list(kwargs.values())
                   if isinstance(a, NDArray) and _is_float(a.dtype)]
            if not dts or len({str(d) for d in dts}) == 1:
                return args, kwargs
            import functools
            import jax.numpy as jnp
            dt = str(functools.reduce(jnp.promote_types, dts))
        else:
            return args, kwargs
        args = tuple(_cast_nd(a, dt) for a in args)
        kwargs = {k: _cast_nd(v, dt) for k, v in kwargs.items()}
        return args, kwargs

    return hook


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally (ref: amp.py:251 init). Idempotent."""
    global _amp_initialized, _target_dtype, _active_lists
    if _amp_initialized:
        return
    target_dtype = str(canonical_dtype(target_dtype))
    assert target_dtype in ("bfloat16", "float16"), \
        "AMP target dtype must be bfloat16 or float16"
    if target_dtype == "float16":
        warnings.warn("float16 AMP on TPU: bfloat16 is the native low "
                      "precision; float16 is emulated and slower")
    target = set(_lists.TARGET_DTYPE_OPS) | set(target_precision_ops or ())
    fp32 = set(_lists.FP32_OPS) | set(fp32_ops or ())
    if conditional_fp32_ops:
        # reference applies these only for certain attr values; we take the
        # conservative route and pin them to fp32
        fp32 |= {op for op, _, _ in conditional_fp32_ops}
    widest = set(_lists.WIDEST_TYPE_CASTS)
    logging.info("Using AMP (target dtype %s)", target_dtype)
    _active_lists = {"target": target, "fp32": fp32, "widest": widest}
    _register.set_amp_cast_hook(_make_hook(target, fp32, widest,
                                           target_dtype))
    _amp_initialized = True
    _target_dtype = target_dtype


def _reset():
    """Testing hook: disable AMP again (the reference cannot — its
    namespace rewrite is one-way)."""
    global _amp_initialized, _target_dtype, _active_lists
    _register.set_amp_cast_hook(None)
    _amp_initialized = False
    _target_dtype = None
    _active_lists = None


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Gluon Trainer and make its update
    step overflow-safe (ref: amp.py:288 init_trainer)."""
    assert _amp_initialized, "call amp.init() before amp.init_trainer()"
    if hasattr(trainer, "_amp_loss_scaler"):
        return
    trainer._amp_loss_scaler = LossScaler()
    trainer._amp_original_scale = trainer._scale
    original_update = trainer._update

    def _amp_update(ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        overflow = scaler.has_overflow(trainer._params)
        if overflow:
            # skip the optimizer step; mark grads consumed so the stale
            # check doesn't fire next iteration
            for param in trainer._params:
                if param.grad_req != "null":
                    param.data()._fresh_grad = False
        else:
            original_update(ignore_stale_grad)
        scaler.update_scale(overflow)

    trainer._update = _amp_update


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss up before backward; trainer rescale undoes it at
    update time (ref: amp.py scale_loss)."""
    if not hasattr(trainer, "_amp_loss_scaler"):
        yield loss
        return
    scale = trainer._amp_loss_scaler.loss_scale
    trainer._scale = trainer._amp_original_scale / scale
    if isinstance(loss, (list, tuple)):
        yield [l * scale for l in loss]
    else:
        yield loss * scale


def unscale(optimizer_or_trainer):
    """Divide gradients by the current loss scale in place and restore the
    trainer's normal rescale so the following step() does not divide by
    the scale a second time (ref: amp.py unscale)."""
    scaler = getattr(optimizer_or_trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise TypeError("optimizer_or_trainer does not have AMP "
                        "loss scaling enabled")
    for param in optimizer_or_trainer._params:
        if param.grad_req != "null":
            g = param.grad()
            g._data = (g._data / scaler.loss_scale)
    optimizer_or_trainer._scale = optimizer_or_trainer._amp_original_scale


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  excluded_sym_names=None, cast_optional_params=False):
    """Convert a symbolic model's params to the target dtype, leaving
    normalization statistics in fp32 (ref: amp.py convert_model → mirrored
    C++ pass src/nnvm/low_precision_pass.cc). With whole-graph XLA compile,
    runtime casts are inserted by the invoke hook, so converting a model is
    a parameter-dtype policy only."""
    excluded = set(excluded_sym_names or [])
    target_dtype = str(canonical_dtype(target_dtype))

    def keep_fp32(name):
        return name in excluded or \
            name.endswith(_NORM_PARAM_SUFFIXES)

    new_args = {k: (v if keep_fp32(k) else v.astype(target_dtype))
                for k, v in arg_params.items()}
    new_aux = dict(aux_params)  # aux = running stats: stay fp32
    return sym, new_args, new_aux


def convert_hybrid_block(block, target_dtype="bfloat16",
                         excluded_sym_names=None,
                         cast_optional_params=False):
    """Cast a Gluon block's parameters to the target dtype, keeping
    normalization layers in fp32 (ref: amp.py convert_hybrid_block)."""
    target_dtype = str(canonical_dtype(target_dtype))
    excluded = set(excluded_sym_names or [])
    for name, param in block.collect_params().items():
        if name in excluded or name.endswith(_NORM_PARAM_SUFFIXES):
            continue
        if param._data is not None and _is_float(param.dtype):
            param.cast(target_dtype)
    return block


def list_lp16_ops(target_dtype=None):
    return sorted(_active_lists["target"]) if _active_lists \
        else list(_lists.TARGET_DTYPE_OPS)


def list_fp32_ops(target_dtype=None):
    return sorted(_active_lists["fp32"]) if _active_lists \
        else list(_lists.FP32_OPS)


def list_widest_type_cast(target_dtype=None):
    return sorted(_active_lists["widest"]) if _active_lists \
        else list(_lists.WIDEST_TYPE_CASTS)
