"""AMP — automatic mixed precision
(ref: python/mxnet/contrib/amp/__init__.py)."""
from .amp import *  # noqa: F401,F403
from .amp import _reset  # noqa: F401  (testing hook)
from .loss_scaler import LossScaler  # noqa: F401
