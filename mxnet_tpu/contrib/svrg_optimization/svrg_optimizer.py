"""SVRG gradient-correction optimizer (ref:
python/mxnet/contrib/svrg_optimization/svrg_optimizer.py).

Wraps a base optimizer; the module feeds it variance-reduced gradients
``g_corrected = g(w) - g(w0) + mu`` where w0 is the epoch snapshot and mu
the full-dataset gradient at w0 (Johnson & Zhang 2013, as in the
reference)."""
from __future__ import annotations

from ... import optimizer as _opt

__all__ = ["_SVRGOptimizer"]


@_opt.register
class _SVRGOptimizer(_opt.Optimizer):
    """ref: svrg_optimizer.py:_SVRGOptimizer — delegates state and update
    math to `default_optimizer`, receiving already-corrected gradients."""

    def __init__(self, default_optimizer="sgd", **kwargs):
        # pull out our own arg; the rest parameterize the base optimizer
        super().__init__(rescale_grad=kwargs.get("rescale_grad", 1.0))
        base_kwargs = dict(kwargs)
        base_kwargs.pop("rescale_grad", None)
        if isinstance(default_optimizer, str):
            self.default_opt = _opt.create(default_optimizer, **base_kwargs)
        else:
            self.default_opt = default_optimizer

    def create_state(self, index, weight):
        return self.default_opt.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self.default_opt.rescale_grad = self.rescale_grad
        return self.default_opt.update(index, weight, grad, state)
