"""SVRGModule: Module with stochastic variance-reduced gradients (ref:
python/mxnet/contrib/svrg_optimization/svrg_module.py).

Same algorithm as the reference: every ``update_freq`` epochs, snapshot
the weights (w0) and compute the full-dataset gradient mu at w0; each
step then updates with ``g(w) - g_w0(batch) + mu``. A second Module bound
to the same symbol holds the snapshot, exactly like the reference's
``_mod_aux``."""
from __future__ import annotations

import logging

from ... import ndarray as nd
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """ref: svrg_module.py:36 SVRGModule(symbol, ..., update_freq)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._param_dict = None   # mu: full grads at the snapshot

    # -- lifecycle (mirror calls onto the snapshot module) -----------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, shared_module,
                               grad_req)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        arg_params, aux_params = self.get_params()
        self._mod_aux.init_params(arg_params=dict(arg_params),
                                  aux_params=dict(aux_params),
                                  allow_missing=False, force_init=True,
                                  allow_extra=False)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        # route through _SVRGOptimizer so the kvstore path matches the
        # reference's special-key scheme in spirit
        params = dict(optimizer_params)
        super().init_optimizer(kvstore=kvstore, optimizer="_svrgoptimizer",
                               optimizer_params=dict(
                                   params, default_optimizer=optimizer),
                               force_init=force_init)

    # -- SVRG machinery ----------------------------------------------------
    def update_full_grads(self, train_data):
        """Snapshot weights into _mod_aux and accumulate the full-dataset
        gradient mu at the snapshot (ref: svrg_module.py update_full_grads)."""
        arg_params, aux_params = self.get_params()
        self._mod_aux.set_params(arg_params=dict(arg_params),
                                 aux_params=dict(aux_params))
        train_data.reset()
        nbatch = 0
        accum = None
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            grads = self._mod_aux._exec_group.executor.grad_dict
            if accum is None:
                accum = {k: g.asnumpy().copy() for k, g in grads.items()}
            else:
                for k, g in grads.items():
                    accum[k] += g.asnumpy()
            nbatch += 1
        assert nbatch > 0, "empty training data"
        self._param_dict = {k: nd.array(v / nbatch)
                            for k, v in accum.items()}
        train_data.reset()

    def forward_backward(self, data_batch):
        """Forward/backward on BOTH modules: main at w, aux at w0
        (ref: svrg_module.py forward_backward)."""
        super().forward_backward(data_batch)
        if self._param_dict is not None:
            self._mod_aux.forward(data_batch, is_train=True)
            self._mod_aux.backward()

    def update(self):
        """Apply the variance-reduced update (ref: svrg_module.py update →
        _update_svrg_gradients)."""
        if self._param_dict is not None:
            self._update_svrg_gradients()
        super().update()

    def _update_svrg_gradients(self):
        g_main = self._exec_group.executor.grad_dict
        g_aux = self._mod_aux._exec_group.executor.grad_dict
        for name, g in g_main.items():
            mu = self._param_dict.get(name)
            g0 = g_aux.get(name)
            if mu is None or g0 is None:
                continue
            g._data = g._data - g0._data + mu._data

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=None, validation_metric=None,
            **kwargs):
        """Training loop with the periodic full-gradient pass
        (ref: svrg_module.py fit). Callback conventions match
        BaseModule.fit: BatchEndParam for batch callbacks,
        (epoch, symbol, arg_params, aux_params) for epoch callbacks."""
        assert num_epoch is not None, "please specify number of epochs"
        from ...metric import create as metric_create
        from ...initializer import Uniform
        from ...model import BatchEndParam
        from ...module.base_module import _as_list
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True)
        self.init_params(initializer=initializer or Uniform(0.01))
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not hasattr(eval_metric, "update"):
            eval_metric = metric_create(eval_metric)
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            train_data.reset()
            eval_metric.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
            name, val = eval_metric.get()
            (self.logger or logging).info("Epoch[%d] Train-%s=%f",
                                          epoch, name, val)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric or eval_metric,
                                 epoch=epoch)
                for name, val in res:
                    (self.logger or logging).info(
                        "Epoch[%d] Validation-%s=%f", epoch, name, val)
