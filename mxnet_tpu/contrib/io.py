"""Contrib data iterators (ref: python/mxnet/contrib/io.py):
DataLoaderIter adapts a Gluon DataLoader to the DataIter interface so
Module-based code can consume it."""
from __future__ import annotations

from .. import ndarray as nd
from ..io import DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Iterate a ``gluon.data.DataLoader`` as a DataIter
    (ref: contrib/io.py:25). The trailing partial batch is zero-padded
    to the full batch size with ``pad`` reporting the fill count —
    keeping every batch the same shape is exactly what the XLA jit
    cache wants."""

    def __init__(self, loader, data_name="data",
                 label_name="softmax_label", dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        data, label = next(self._iter)
        self.batch_size = data.shape[0]
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, tuple(data.shape), dtype)]
        self.provide_label = [DataDesc(label_name, tuple(label.shape),
                                       dtype)]
        self._current_batch = None
        self.reset()

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        try:
            self._current_batch = next(self._iter)
        except StopIteration:
            self._current_batch = None
        return self._current_batch is not None

    def _padded(self, arr):
        pad = self.getpad()
        arr = arr.astype(self.dtype)
        if not pad:
            return [arr]
        full = nd.zeros((self.batch_size,) + tuple(arr.shape[1:]),
                        dtype=self.dtype)
        full[:arr.shape[0]] = arr
        return [full]

    def getdata(self):
        return self._padded(self._current_batch[0])

    def getlabel(self):
        return self._padded(self._current_batch[1])

    def getpad(self):
        return self.batch_size - self._current_batch[0].shape[0]

    def getindex(self):
        return None
