"""TensorBoard bridge (ref: python/mxnet/contrib/tensorboard.py).

The reference's LogMetricsCallback requires the `tensorboard` pip
package purely to write scalar summaries. TensorBoard's on-disk format
is just TFRecord-framed Event protobufs, so this module writes them
directly — same dependency-free stance as the ONNX bridge
(contrib/onnx/proto.py): Event{1:wall_time(double), 2:step(int64),
5:summary}, Summary{1: repeated Value{1:tag, 2:simple_value(float)}},
TFRecord framing = u64 length + masked crc32c(length) + payload +
masked crc32c(payload).
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]


# -- crc32c (Castagnoli), table-driven — required by TFRecord framing ------

_CRC_TABLE = []  # mxlint: disable=MX003 (idempotent lazy init of a deterministic table; a racing double build appends identical values — reads go through the final 256 entries only)


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# -- minimal Event/Summary protobuf encoding -------------------------------

def _varint(out, v):
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _tag(out, field, wire):
    _varint(out, (field << 3) | wire)


def _scalar_event(tag, value, step, wall_time):
    val = bytearray()                      # Summary.Value
    _tag(val, 1, 2)                        # tag (string)
    t = tag.encode()
    _varint(val, len(t))
    val.extend(t)
    _tag(val, 2, 5)                        # simple_value (float)
    val.extend(struct.pack("<f", float(value)))

    summ = bytearray()                     # Summary
    _tag(summ, 1, 2)
    _varint(summ, len(val))
    summ.extend(val)

    ev = bytearray()                       # Event
    _tag(ev, 1, 1)                         # wall_time (double)
    ev.extend(struct.pack("<d", wall_time))
    _tag(ev, 2, 0)                         # step (int64)
    _varint(ev, int(step))
    _tag(ev, 5, 2)                         # summary
    _varint(ev, len(summ))
    ev.extend(summ)
    return bytes(ev)


def _tfrecord(payload):
    hdr = struct.pack("<Q", len(payload))
    return (hdr + struct.pack("<I", _masked_crc(hdr)) + payload
            + struct.pack("<I", _masked_crc(payload)))


class SummaryWriter:
    """Append-only scalar event writer, tensorboard-loadable.
    API shape follows tensorboard.SummaryWriter.add_scalar."""

    def __init__(self, logdir):
        import socket
        os.makedirs(logdir, exist_ok=True)
        # hostname+pid+counter keep concurrent writers (multi-process
        # ranks, back-to-back constructions) in separate files — the
        # upstream format embeds them for the same reason
        SummaryWriter._seq = getattr(SummaryWriter, "_seq", 0) + 1
        fname = "events.out.tfevents.%d.%s.%d.%d.mxnet_tpu" % (
            int(time.time()), socket.gethostname(), os.getpid(),
            SummaryWriter._seq)
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "ab")
        # file-version header event expected by TB readers
        ver = bytearray()
        _tag(ver, 1, 1)
        ver.extend(struct.pack("<d", time.time()))
        _tag(ver, 3, 2)                    # file_version (string)
        fv = b"brain.Event:2"
        _varint(ver, len(fv))
        ver.extend(fv)
        self._f.write(_tfrecord(bytes(ver)))
        self._f.flush()

    def add_scalar(self, tag, value, global_step=0):
        self._f.write(_tfrecord(_scalar_event(tag, value, global_step,
                                              time.time())))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class LogMetricsCallback(object):
    """Batch-end callback logging EvalMetric values to TensorBoard
    (ref: contrib/tensorboard.py LogMetricsCallback — same constructor
    and __call__(param) protocol, driven by Speedometer-style
    BatchEndParam objects)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
        self.summary_writer.flush()
