"""Legacy experimental autograd API (ref: python/mxnet/contrib/autograd.py)
— thin aliases over the first-class `mxnet_tpu.autograd` tape."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..ndarray import NDArray, zeros_like

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """ref: contrib/autograd.py:32 — returns the previous state."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    _ag.set_recording(is_train)
    return prev


def train_section():
    """`with train_section():` records in train mode
    (ref: contrib/autograd.py:74)."""
    return _ag.record(train_mode=True)


def test_section():
    """ref: contrib/autograd.py:88."""
    return _ag.pause(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: contrib/autograd.py:102."""
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    """ref: contrib/autograd.py:123."""
    return _ag.backward(outputs, head_grads=out_grads,
                        retain_graph=retain_graph)


def compute_gradient(outputs):
    """ref: contrib/autograd.py:158."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Wrap ``func`` to return (arg gradients, loss)
    (ref: contrib/autograd.py:163)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            idx = argnum if isinstance(argnum, list) else [argnum]
            variables = [args[i] for i in idx]
        for x in variables:
            assert isinstance(x, NDArray), \
                "autograd input should be NDArray"
        grads = [zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        compute_gradient([outputs] if isinstance(outputs, NDArray)
                         else outputs)
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Wrap ``func`` to return arg gradients only
    (ref: contrib/autograd.py:195)."""
    wrapped = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def only_grads(*args):
        return wrapped(*args)[0]
    return only_grads
