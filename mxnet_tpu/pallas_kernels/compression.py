"""2-bit gradient compression kernels.

Semantics match the reference exactly (ref:
src/kvstore/gradient_compression-inl.h:40 quantize_2bit struct): each
value becomes 2 bits — ``11`` if ``residual + grad >= threshold`` (decodes
to +threshold), ``10`` if ``<= -threshold`` (decodes to -threshold), else
``00`` (decodes to 0) — with error-feedback residual accumulation. 16
values pack into one 32-bit word.

Layout note: the reference packs value i of a 16-group into byte ``i>>2``
bit-pair ``i&3`` of a float32 reinterpreted as chars; here the container
is an int32 with value i at bit-pair ``15-i`` (big-endian-in-word). The
wire format is internally consistent between quantize/dequantize and 4x
denser than fp32 either way — DCN-bound pushes ship 1/16 the bytes.

The Pallas version tiles words over a (rows, 128) lane layout so the
pack/unpack shift-or runs fully on the VPU; the jnp fallback is identical
math and serves CPU + autodiff-free paths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["quantize_2bit", "dequantize_2bit", "quantize_2bit_jnp",
           "dequantize_2bit_jnp"]

_GROUP = 16  # values per 32-bit word


def _pad_to(x, multiple):
    n = x.shape[0]
    rem = (-n) % multiple
    if rem:
        x = jnp.concatenate([x, jnp.zeros((rem,), x.dtype)])
    return x


def quantize_2bit_jnp(grad, residual, threshold=0.5):
    """Returns (compressed int32 [ceil(n/16)], new_residual [n])."""
    n = grad.shape[0]
    r = residual + grad
    pos = r >= threshold
    neg = r <= -threshold
    codes = jnp.where(pos, 3, jnp.where(neg, 2, 0)).astype(jnp.int32)
    new_residual = r - pos * threshold + neg * threshold
    codes = _pad_to(codes, _GROUP).reshape(-1, _GROUP)
    shifts = 2 * (15 - jnp.arange(_GROUP, dtype=jnp.int32))
    # bit-pairs are disjoint, so sum == bitwise-or
    words = jnp.sum(codes << shifts[None, :], axis=1, dtype=jnp.int32)
    return words, new_residual[:n]


def dequantize_2bit_jnp(words, n, threshold=0.5):
    """Inverse of quantize_2bit_jnp: int32 words -> float32 [n]."""
    shifts = 2 * (15 - jnp.arange(_GROUP, dtype=jnp.int32))
    codes = (words[:, None] >> shifts[None, :]) & 3
    vals = jnp.where(codes == 3, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))
    return vals.reshape(-1)[:n].astype(jnp.float32)


# -- Pallas versions --------------------------------------------------------

_LANES = 128


def _quant_kernel(r_ref, words_ref, newr_ref, *, threshold):
    # r_ref: (16, W) — row i holds bit-pair 15-i's values for each word
    r = r_ref[:]
    pos = r >= threshold
    neg = r <= -threshold
    codes = jnp.where(pos, 3, jnp.where(neg, 2, 0)).astype(jnp.int32)
    newr_ref[:] = r - pos.astype(r.dtype) * threshold \
        + neg.astype(r.dtype) * threshold
    shifts = 2 * (15 - jax.lax.broadcasted_iota(jnp.int32, codes.shape, 0))
    words_ref[:] = jnp.sum(codes << shifts, axis=0, keepdims=True)


def _dequant_kernel(words_ref, out_ref, *, threshold):
    words = words_ref[:]                       # (1, W)
    shifts = 2 * (15 - jax.lax.broadcasted_iota(
        jnp.int32, (_GROUP,) + words.shape[1:], 0))
    codes = (words >> shifts) & 3              # (16, W)
    out_ref[:] = jnp.where(
        codes == 3, jnp.float32(threshold),
        jnp.where(codes == 2, jnp.float32(-threshold), jnp.float32(0.0)))


def _pallas_ok():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def quantize_2bit(grad, residual, threshold=0.5, interpret=False):
    """2-bit quantize with error feedback. grad/residual: float32 [n].
    Pallas on TPU, jnp elsewhere. Both produce identical words."""
    if not (interpret or _pallas_ok()):
        return quantize_2bit_jnp(grad, residual, threshold)
    import jax.experimental.pallas as pl

    n = grad.shape[0]
    r = _pad_to(residual + grad, _GROUP * _LANES)
    nwords = r.shape[0] // _GROUP
    # word w value i lives at flat index w*16+i → (nwords, 16) → T (16, W)
    r2 = r.reshape(nwords, _GROUP).T
    words, newr = pl.pallas_call(
        functools.partial(_quant_kernel, threshold=float(threshold)),
        grid=(nwords // _LANES,),
        in_specs=[pl.BlockSpec((_GROUP, _LANES), lambda i: (0, i))],
        out_specs=[pl.BlockSpec((1, _LANES), lambda i: (0, i)),
                   pl.BlockSpec((_GROUP, _LANES), lambda i: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((1, nwords), jnp.int32),
                   jax.ShapeDtypeStruct((_GROUP, nwords), jnp.float32)],
        interpret=interpret,
    )(r2)
    # trim lane padding: the wire format is ceil(n/16) words, identical to
    # the jnp path
    out_words = (n + _GROUP - 1) // _GROUP
    return words.reshape(-1)[:out_words], newr.T.reshape(-1)[:n]


def dequantize_2bit(words, n, threshold=0.5, interpret=False):
    if not (interpret or _pallas_ok()):
        return dequantize_2bit_jnp(words, n, threshold)
    import jax.experimental.pallas as pl

    nwords = words.shape[0]
    pad = (-nwords) % _LANES
    if pad:
        words = jnp.concatenate([words, jnp.zeros((pad,), words.dtype)])
    total = words.shape[0]
    out = pl.pallas_call(
        functools.partial(_dequant_kernel, threshold=float(threshold)),
        grid=(total // _LANES,),
        in_specs=[pl.BlockSpec((1, _LANES), lambda i: (0, i))],
        out_specs=pl.BlockSpec((_GROUP, _LANES), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((_GROUP, total), jnp.float32),
        interpret=interpret,
    )(words.reshape(1, total))
    return out.T.reshape(-1)[:n]
