"""Fused multi-tensor optimizer apply as a Pallas TPU kernel.

The fused train step (gluon/fused_step.py) traces one ``step_fn`` call
per parameter — for a ResNet/transformer that is hundreds of small
elementwise op chains XLA schedules as separate fusions, each paying
its own HBM round trip and launch. This module restores the reference's
multi-tensor apply shape (ref: src/operator/contrib/multi_sum_sq.cu +
multi_sgd/multi_lamb fused update kernels): the parameter tree is
flattened into dtype-homogeneous packed segments (the
``parallel/overlap.py:bucket_plan`` shape — same size cap, same
order-preserving dtype grouping) and the optimizer math runs as ONE
kernel launch per bucket over the packed 1-D views.

Bitwise parity contract: every supported ``step_fn``
(``Optimizer.fused_apply_supported``; SGD/momentum and Adam) is purely
ELEMENTWISE over (weight, grad, state..., lr, wd, rescale). Packing
therefore changes only the array SHAPE the math runs over, never a
single rounding: concatenation and splitting are exact, per-parameter
lr/wd scalars become per-element vectors holding the identical values,
and the kernel body calls the optimizer's own ``step_fn`` on the packed
block — so packed results are bitwise-equal to the per-parameter chain
(gated in ``BENCH_MODEL=fused_kernels`` and tests).

Consumed by ``gluon/fused_step.py``'s update phase behind
``MXTPU_FUSED_APPLY`` (default off; ``1`` packs, ``interpret`` forces
the Pallas kernel in interpreter mode for CPU tests). Off-TPU the
packed segments still run — as one jnp elementwise chain per bucket,
which XLA fuses into one program instead of per-parameter op chains.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ._compile_attr import attributed
from ..base import getenv as _getenv
from .conv_fused import _use_pallas

__all__ = ["packed_apply", "packed_apply_reference", "enabled",
           "bucketize"]

_ENV = "MXTPU_FUSED_APPLY"


def _setting():
    return _getenv(_ENV, "0")


def enabled():
    return _setting() != "0"


def _force_interpret():
    return _setting() == "interpret"


def bucketize(ws):
    """Dtype-homogeneous, size-capped packing plan over the weight
    leaves — literally ``parallel/overlap.bucket_plan`` (one shared
    definition of how this framework groups a param tree into flat
    segments, whether for wire messages or kernel launches)."""
    from ..parallel.overlap import bucket_plan
    return bucket_plan(ws)


# ---------------------------------------------------------------------------
# Pallas kernel: one elementwise apply over a packed (rows, 128) segment
# ---------------------------------------------------------------------------

from jax.experimental import pallas as pl                # noqa: E402
from jax.experimental.pallas import tpu as pltpu         # noqa: E402

_LANES = 128
_ROW_TILE = 512


def _apply_kernel(*refs, n_state, n_out, math):
    w_ref, g_ref, lr_ref, wd_ref, rs_ref = refs[:5]
    s_refs = refs[5:5 + n_state]
    out_refs = refs[5 + n_state:5 + n_state + n_out]
    rs = rs_ref[0, 0].astype(w_ref.dtype)
    outs = math(w_ref[:], g_ref[:], tuple(s[:] for s in s_refs),
                lr_ref[:], wd_ref[:], rs)
    for o_ref, o in zip(out_refs, outs):
        o_ref[:] = o.astype(o_ref.dtype)


def _sublane(dtype):
    b = jnp.dtype(dtype).itemsize
    return 8 if b >= 4 else (16 if b == 2 else 32)


def _pad_rows(flat, rows, dtype):
    pad = rows * _LANES - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat.reshape(rows, _LANES)


def _pallas_apply(math, w, g, sleaves, lrv, wdv, rescale, out_structs,
                  interpret):
    L = w.shape[0]
    dt = w.dtype
    q = _sublane(dt)
    rows = pl.cdiv(L, _LANES)
    tr = min(_ROW_TILE, ((rows + q - 1) // q) * q)
    rows = ((rows + tr - 1) // tr) * tr
    n_state = len(sleaves)
    n_out = len(out_structs)
    ops = [_pad_rows(a, rows, a.dtype)
           for a in (w, g, lrv, wdv)] + \
        [jnp.asarray(rescale, jnp.float32).reshape(1, 1)] + \
        [_pad_rows(s, rows, s.dtype) for s in sleaves]
    blk = pl.BlockSpec((tr, _LANES), lambda r: (r, 0))
    outs = attributed(
        "optimizer_apply", (L, str(dt), n_state, n_out), lambda:
        pl.pallas_call(
            functools.partial(_apply_kernel, n_state=n_state,
                              n_out=n_out, math=math),
            grid=(rows // tr,),
            in_specs=[blk, blk, blk, blk,
                      pl.BlockSpec((1, 1), lambda r: (0, 0),
                                   memory_space=pltpu.SMEM)]
            + [blk] * n_state,
            out_specs=tuple([blk] * n_out),
            out_shape=tuple(
                jax.ShapeDtypeStruct((rows, _LANES), s.dtype)
                for s in out_structs),
            interpret=interpret,
        )(ops[0], ops[1], ops[2], ops[3], ops[4], *ops[5:]))
    return [o.reshape(-1)[:L] for o in outs]


def packed_apply_reference(math, w, g, sleaves, lrv, wdv, rescale):
    """The packed apply without the kernel: the optimizer's own
    ``step_fn`` over the flat segment — one jnp elementwise chain XLA
    fuses per bucket. Bitwise-identical to the kernel (same math, same
    operands) and to the per-parameter chain (elementwise argument in
    the module docstring)."""
    rs = jnp.asarray(rescale, jnp.float32).astype(w.dtype)
    return list(math(w, g, tuple(sleaves), lrv, wdv, rs))


def packed_apply(opt, ws, gs, states, lrs, wds, rescale,
                 interpret=False):
    """Apply ``opt.step_fn`` to every parameter in ONE launch per
    packed segment.

    ws/gs: lists of weight/grad arrays (any shapes, mixed dtypes).
    states: per-parameter optimizer-state pytrees, structurally
    identical across the list and with every leaf shaped/typed like its
    weight (the caller — gluon/fused_step — checks eligibility).
    lrs/wds: per-parameter f32 scalars (traced operands); rescale: f32
    scalar. Returns ``(new_ws, new_states)`` lists, bitwise-equal to
    looping ``opt.step_fn`` per parameter.
    """
    interpret = bool(interpret) or _force_interpret()
    n = len(ws)
    new_ws = [None] * n
    new_states = [None] * n
    treedef = jax.tree_util.tree_structure(states[0]) if n else None
    for bucket in bucketize(ws):
        dt = ws[bucket[0]].dtype
        sizes = [int(ws[i].size) for i in bucket]

        def cat(parts):
            parts = [jnp.ravel(p) for p in parts]
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        w = cat([ws[i] for i in bucket])
        g = cat([gs[i] for i in bucket])
        sleaves_per = [jax.tree_util.tree_leaves(states[i])
                       for i in bucket]
        sleaves = [cat([sl[k] for sl in sleaves_per])
                   for k in range(len(sleaves_per[0]))]
        # per-parameter scalars -> per-element vectors with the exact
        # same values; demoted to the bucket dtype exactly where the
        # per-parameter loop demotes (non-f32 weights). The vectors add
        # two param-sized operands per bucket — the price of keeping
        # the bitwise-parity argument trivially elementwise; a
        # per-segment SMEM scalar table would carry the same values
        # with less HBM traffic but per-element indexing in the kernel
        # (revisit if the TPU gate's >=1.5x headroom ever thins)
        lrv = cat([jnp.broadcast_to(jnp.asarray(lrs[i], jnp.float32),
                                    (sz,)) for i, sz in zip(bucket, sizes)])
        wdv = cat([jnp.broadcast_to(jnp.asarray(wds[i], jnp.float32),
                                    (sz,)) for i, sz in zip(bucket, sizes)])
        if dt != jnp.float32:
            lrv = lrv.astype(dt)
            wdv = wdv.astype(dt)

        def math(w_, g_, sl_, lr_, wd_, rs_):
            state = jax.tree_util.tree_unflatten(treedef, list(sl_))
            nw, ns = opt.step_fn(w_, g_, state, lr_, wd_, rs_)
            ns_leaves = jax.tree_util.tree_leaves(ns)
            if len(ns_leaves) != len(sl_):
                raise ValueError(
                    "%s.step_fn changed the state structure — not "
                    "packable" % type(opt).__name__)
            return (nw,) + tuple(ns_leaves)

        def _sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        out_structs = jax.eval_shape(
            math, _sds(w), _sds(g), tuple(_sds(s) for s in sleaves),
            _sds(lrv), _sds(wdv), jax.ShapeDtypeStruct((), dt))
        if interpret or _use_pallas(w):
            outs = _pallas_apply(math, w, g, sleaves, lrv, wdv, rescale,
                                 out_structs, interpret)
        else:
            outs = packed_apply_reference(math, w, g, sleaves, lrv, wdv,
                                          rescale)
        # split the packed results back into per-parameter views; the
        # state structure is unchanged by contract (asserted in math)
        nw_flat, ns_flats = outs[0], outs[1:]
        off = 0
        for i, sz in zip(bucket, sizes):
            new_ws[i] = nw_flat[off:off + sz].reshape(ws[i].shape)
            leaves = [f[off:off + sz].reshape(ws[i].shape)
                      for f in ns_flats]
            new_states[i] = jax.tree_util.tree_unflatten(treedef, leaves)
            off += sz
    return new_ws, new_states
