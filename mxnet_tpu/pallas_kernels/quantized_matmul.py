"""int8 x int8 -> int32 tiled matmul with per-channel scales, as a
Pallas TPU kernel.

The quantized operator family (ops/quantized.py, mirroring the
reference's src/operator/quantization/) has been pure-XLA since its
port: ``quantized_fully_connected``/``quantized_conv`` cast int8
payloads up to int32 and run a float-path ``dot_general``. On TPU the
MXU has a native int8 path (2x the bf16 rate on v5e) that XLA only
picks when it sees int8 operands with an int32 accumulator — this
kernel guarantees that shape:

- grid (M/TM, N/TN, K/TK) with K innermost; an (TM, TN) int32 VMEM
  scratch accumulates ``dot(int8, int8, preferred_element_type=int32)``
  partials across the K sweep and writes once at the last K tile —
  int8 operand tiles move through VMEM exactly once.
- optional per-output-channel dequantize fused into the epilogue: with
  ``scales`` (f32 (N,), = input_scale * per-channel weight scale) the
  kernel writes f32 ``acc * scales`` instead of raw int32, so a
  serving path gets dequantized activations without a second HBM pass.

Integer accumulation is EXACT, so kernel-vs-reference parity is
bitwise on the int32 payload (the ``BENCH_MODEL=fused_kernels`` gate
checks equality, not a ULP bound); the scaled f32 epilogue is one
correctly-rounded multiply per element.

Consumed by ``ops/quantized.py`` ``quantized_fully_connected`` (always,
when shapes fit) and ``quantized_conv`` (1x1/stride-1 convolutions —
the ResNet bottleneck reductions that dominate quantized inference),
behind ``MXTPU_QUANT_MATMUL``. The ``resnet50_infer`` bench picks this
up through ``contrib.quantization.quantize_net``.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from ._compile_attr import attributed
from ..base import getenv as _getenv
from .conv_fused import _use_pallas

__all__ = ["quantized_matmul", "quantized_matmul_reference", "engaged"]

_ENV = "MXTPU_QUANT_MATMUL"


def _setting():
    return _getenv(_ENV, "1")


def _force_interpret():
    return _setting() == "interpret"


def quantized_matmul_reference(x, w, scales=None):
    """jnp semantics of the kernel (fallback + goldens): x (M, K) int8,
    w (K, N) int8 -> (M, N) int32 accumulator, or f32 ``acc * scales``
    with per-output-channel scales (N,) f32."""
    acc = lax.dot_general(x.astype(jnp.int32), w.astype(jnp.int32),
                          (((1,), (0,)), ((), ())))
    if scales is None:
        return acc
    return acc.astype(jnp.float32) * scales


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

from jax.experimental import pallas as pl                # noqa: E402
from jax.experimental.pallas import tpu as pltpu         # noqa: E402

_VMEM_BUDGET = 7 * 1024 * 1024


def _mm_kernel(x_ref, w_ref, o_ref, acc_scr, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == nk - 1)
    def _write():
        o_ref[:] = acc_scr[:]


def _mm_scaled_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == nk - 1)
    def _write():
        o_ref[:] = acc_scr[:].astype(jnp.float32) * s_ref[:]


def _tiles(M, K, N):
    """(TM, TN, TK, fits). int8 tiling quanta: 32 sublanes, 128 lanes.
    The VMEM working set (double-buffered int8 operand tiles + the
    int32 accumulator + the output tile) stays comfortably inside the
    budget at the default 128^3 tiling; M shrinks to the largest
    32-multiple tile that divides it (small batches), K/N require lane
    alignment outright — anything else falls back to the reference."""
    tm = 128
    while tm > 32 and M % tm != 0:
        tm //= 2
    tk = 128 if K % 128 == 0 else 0
    tn = 128 if N % 128 == 0 else 0
    if not tk or not tn or M % tm != 0:
        return tm, tn, tk, False
    est = 2 * (tm * tk + tk * tn) + tm * tn * (4 + 2 * 4)
    return tm, tn, tk, est <= _VMEM_BUDGET


def _fits(M, K, N):
    return _tiles(M, K, N)[3]


def _pallas_matmul(x, w, scales, interpret):
    M, K = x.shape
    N = w.shape[1]
    if interpret:
        tm, tn, tk = min(128, M), min(128, N), min(128, K)
        if M % tm or N % tn or K % tk:
            tm, tn, tk = M, N, K
    else:
        tm, tn, tk, _ = _tiles(M, K, N)
    nk = K // tk
    key = (M, K, N, scales is not None)
    grid = (M // tm, N // tn, nk)
    x_spec = pl.BlockSpec((tm, tk), lambda i, j, k: (i, k))
    w_spec = pl.BlockSpec((tk, tn), lambda i, j, k: (k, j))
    o_spec = pl.BlockSpec((tm, tn), lambda i, j, k: (i, j))
    scratch = [pltpu.VMEM((tm, tn), jnp.int32)]
    if scales is None:
        return attributed("quantized_matmul", key, lambda:
            pl.pallas_call(
                functools.partial(_mm_kernel, nk=nk),
                grid=grid, in_specs=[x_spec, w_spec], out_specs=o_spec,
                out_shape=jax.ShapeDtypeStruct((M, N), jnp.int32),
                scratch_shapes=scratch, interpret=interpret,
            )(x, w))
    s2 = scales.astype(jnp.float32).reshape(1, N)
    return attributed("quantized_matmul", key, lambda:
        pl.pallas_call(
            functools.partial(_mm_scaled_kernel, nk=nk),
            grid=grid,
            in_specs=[x_spec, w_spec,
                      pl.BlockSpec((1, tn), lambda i, j, k: (0, j))],
            out_specs=o_spec,
            out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
            scratch_shapes=scratch, interpret=interpret,
        )(x, w, s2))


def engaged(x, w):
    """Whether ops/quantized.py should route this (M, K) x (K, N) int8
    product through the kernel: enabled, int8 payloads, and either on
    TPU with an aligned tiling or force-interpreted
    (``MXTPU_QUANT_MATMUL=interpret``, the CPU test hook)."""
    if _setting() == "0":
        return False
    if x.ndim != 2 or w.ndim != 2 or x.shape[1] != w.shape[0]:
        return False
    if jnp.dtype(x.dtype) != jnp.int8 or jnp.dtype(w.dtype) != jnp.int8:
        return False
    if _force_interpret():
        return True
    return _use_pallas(x) and _fits(x.shape[0], x.shape[1], w.shape[1])


def quantized_matmul(x, w, scales=None, interpret=False):
    """x (M, K) int8 @ w (K, N) int8 with int32 accumulation on the MXU
    int path. Returns the (M, N) int32 accumulator, or — with per-
    output-channel ``scales`` (N,) f32 — the dequantized f32 product
    ``acc * scales`` fused into the kernel epilogue. Falls back to an
    identical-semantics jnp reference off-TPU or for unaligned shapes;
    ``interpret=True`` runs the Pallas kernel in interpreter mode for
    CPU tests.
    """
    if x.ndim != 2 or w.ndim != 2 or x.shape[1] != w.shape[0]:
        raise ValueError("quantized_matmul: need (M, K) x and (K, N) w, "
                         "got %s / %s" % (x.shape, w.shape))
    interpret = bool(interpret) or _force_interpret()
    if interpret or (_use_pallas(x)
                     and _fits(x.shape[0], x.shape[1], w.shape[1])):
        return _pallas_matmul(x, w, scales, interpret)
    return quantized_matmul_reference(x, w, scales)
