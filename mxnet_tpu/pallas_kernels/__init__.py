"""Hand-written Pallas TPU kernels.

Analog slot of the reference's custom CUDA kernels + NVRTC runtime
compilation (ref: src/common/rtc.cc, src/operator/nn/cudnn/,
src/kvstore/gradient_compression.cu): ops where XLA's automatic fusion
isn't enough get explicit MXU/VMEM tiling here. Everything has a pure
jnp fallback so CPU runs (and the virtual-device test mesh) work
unchanged; on TPU the Pallas path is selected automatically.
"""
from .flash_attention import flash_attention  # noqa: F401
from .compression import (quantize_2bit, dequantize_2bit,  # noqa: F401
                          quantize_2bit_jnp, dequantize_2bit_jnp)
