"""Hand-written Pallas TPU kernels.

Analog slot of the reference's custom CUDA kernels + NVRTC runtime
compilation (ref: src/common/rtc.cc, src/operator/nn/cudnn/,
src/kvstore/gradient_compression.cu): ops where XLA's automatic fusion
isn't enough get explicit MXU/VMEM tiling here. Everything has a pure
jnp fallback so CPU runs (and the virtual-device test mesh) work
unchanged; on TPU the Pallas path is selected automatically.

Module contract (enforced by mxlint MX012): every kernel module
exports a reference implementation (``*_reference`` / ``*_jnp``) with
identical semantics, takes an ``interpret=`` path so the CPU tier-1
suite runs the real kernel code in interpreter mode, and is registered
in ``KERNEL_BENCH`` below — the map from kernel module to the
``BENCH_MODEL`` that prices it (``fused_kernels`` is the shared gate
for the PR 9 campaign kernels: >=1.5x vs the XLA baseline on a real
backend, interpret-mode parity + ULP/bitwise bound on CPU). Kernel
first-builds register in ``profiler.record_compile`` via
``_compile_attr.attributed`` and appear in the Compile table
(docs/OBSERVABILITY.md).
"""
from .flash_attention import flash_attention  # noqa: F401
from .compression import (quantize_2bit, dequantize_2bit,  # noqa: F401
                          quantize_2bit_jnp, dequantize_2bit_jnp)
from .batchnorm_fused import fused_batch_norm  # noqa: F401
from .optimizer_apply import packed_apply  # noqa: F401
from .quantized_matmul import quantized_matmul  # noqa: F401

# kernel module -> the BENCH_MODEL whose gate prices it (mxlint MX012
# requires every kernel module to appear here; bench.py
# BENCH_MODEL=fused_kernels iterates the 'fused_kernels' entries)
KERNEL_BENCH = {
    "flash_attention": "transformer",
    "compression": "comm_overlap",
    "conv_fused": "resnet50",
    "batchnorm_fused": "fused_kernels",
    "optimizer_apply": "fused_kernels",
    "quantized_matmul": "fused_kernels",
}
