"""Fused scale-bias-ReLU + 3x3 convolution as Pallas TPU kernels.

Why this kernel exists: XLA:TPU fuses elementwise producers into DOT
operand loads but NOT into convolutions (measured compiler-exact in
benchmark/fusion_probe.py: a conv consuming relu(x*s+b) moves 2.6x the
bytes of the equivalent dot). In a ResNet bottleneck the BN-apply+ReLU
chain between convs therefore materializes a full activation tensor to
HBM on the XLA path — and the step is HBM-bandwidth-bound (44 GB/step at
~880 GB/s, docs/ROADMAP.md "ResNet perf ceiling"). This kernel computes

    y = conv3x3(relu(x * s + b), W)        # stride 1, pad 1, NHWC

reading ``x`` (the raw previous conv output) straight from HBM and
applying the normalize/ReLU chain in VMEM, so the normalized activation
never exists in HBM in either direction:

- forward: NB images per grid cell (NB>1 for small feature maps so the
  MXU sees >=~400 rows); scale/bias/ReLU on the VPU in the compute
  dtype, then ONE dot_general over im2col patches built in VMEM —
  (NB*H*W, 9*Ci) against (9*Ci, Co) — so even Ci=64 layers present a
  576-deep contraction to the 128x128 MXU instead of nine thin dots.
- backward: two kernels in the same shape. d-input recomputes the ReLU
  mask from x and contracts shifted dy patches against the
  flipped-transposed weights ((NB*H*W, 9*Co) x (9*Co, Ci)); d-weight
  recomputes z = relu(x*s+b) in VMEM and accumulates the (9*Ci, Co)
  cotangent across the sequential batch grid in a VMEM-resident f32
  block (Co-tiled to fit). Per-channel ds/db partials accumulate the
  same way, so the only HBM traffic is one read of x and dy each per
  kernel.

Measured reality (v5e, b128, pipelined long-run): the explicit im2col
costs ~9x the activation bytes in VMEM copy traffic, which XLA's native
windowed conv avoids — so the fused kernel only BEATS the unfused
XLA chain on small feature maps where XLA's conv is least efficient
(7x7x512: 46 vs 37 TF/s effective; 56x56x64: 26 vs 47 — XLA wins).
The model-level fuse="auto" policy therefore applies the kernel to
deep stages only; see docs/ROADMAP.md for the full study.

The reference's closest analog is the cuDNN fused conv-bias-activation
path (ref: src/operator/nn/convolution.cu + fused op in
src/operator/fusion/fused_op.cu); the TPU-native design fuses the
*producer* side instead because that is the fusion XLA cannot do.

Used by the ``fuse=True|"auto"`` ResNet variants
(gluon/model_zoo/vision/resnet.py; "auto" = deep stages only, the
measured winning policy) and exposed functionally here.
Non-TPU backends (and any shape the kernel does not cover) fall back to
a jnp reference with identical semantics; ``interpret=True`` runs the
Pallas kernels in interpreter mode for CPU tests.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from ..base import getenv as _getenv

__all__ = ["fused_scale_relu_conv3x3", "fused_conv_reference"]


def _compute_dtype(x_dtype):
    """MXU input dtype: keep bf16 (full-rate), promote other halfs to
    f32-safe bf16, leave f32 alone."""
    d = jnp.dtype(x_dtype)
    if d == jnp.bfloat16 or d == jnp.float32:
        return d
    if d.itemsize <= 2:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(jnp.float32)


def fused_conv_reference(x, s, b, w, relu=True):
    """jnp semantics of the fused op (fallback + autodiff + goldens).

    x: (N, H, W, Ci) — raw producer output (e.g. pre-BN conv out)
    s, b: (Ci,) f32 — folded BN scale/bias (s = gamma*rsqrt(var+eps))
    w: (3, 3, Ci, Co) HWIO
    """
    cdt = _compute_dtype(x.dtype)
    xc = x.astype(cdt)
    pre = xc * s.astype(cdt) + b.astype(cdt)
    z = jnp.maximum(pre, jnp.zeros((), cdt)) if relu else pre
    out = lax.conv_general_dilated(
        z, w.astype(z.dtype), window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _act(x, s, b, relu, cdt):
    """Scale-bias(-ReLU) in the compute dtype. For bf16 inputs the whole
    chain runs in bf16 — one fused VPU pass instead of three (cast-up,
    f32 math, cast-down), and the same precision class as the reference
    BN-apply which computes (x-mean)*inv*g+beta in x.dtype."""
    xc = x.astype(cdt)
    pre = xc * s.astype(cdt) + b.astype(cdt)
    return jnp.maximum(pre, jnp.zeros((), cdt)) if relu else pre


def _fill_patches(zp_scr, pat_scr, i, src, H, W, C, cdt):
    """im2col inside VMEM: zero-pad ``src`` into zp_scr, then write the 9
    shifted (H, W, C) views into pat_scr[i] channel-blocks -> (H, W, 9C),
    tap-major channel order matching w.reshape(9*Ci, Co). Explicit
    scratch stores — a 9-way jnp.concatenate of the same views hangs the
    Mosaic compiler (measured >300s vs 1.3s for this form)."""
    zp_scr[:] = jnp.zeros_like(zp_scr)
    zp_scr[1:H + 1, 1:W + 1, :] = src.astype(cdt)
    for ky in range(3):
        for kx in range(3):
            t = (ky * 3 + kx) * C
            pat_scr[i, :, :, t:t + C] = zp_scr[ky:ky + H, kx:kx + W, :]


def _fwd_kernel(x_ref, s_ref, b_ref, w_ref, o_ref, zp_scr, pat_scr, *,
                NB, H, W, relu, cdt):
    # grid is (co_tiles, n): the im2col patches are rebuilt per Co tile
    # (VPU cost) so the weight block (9Ci x TCo) fits VMEM at depth
    Ci = x_ref.shape[-1]
    for i in range(NB):
        z = _act(x_ref[i], s_ref[0], b_ref[0], relu, cdt)
        _fill_patches(zp_scr, pat_scr, i, z, H, W, Ci, cdt)
    acc = lax.dot_general(                           # (NB*H*W, TCo)
        pat_scr[:].reshape(NB * H * W, 9 * Ci), w_ref[:],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    o_ref[:] = acc.reshape(NB, H, W, w_ref.shape[-1]).astype(o_ref.dtype)


def _bwd_dx_kernel(x_ref, s_ref, b_ref, wt_ref, g_ref, dx_ref, ds_ref,
                   db_ref, gp_scr, pat_scr, *, NB, H, W, relu, cdt):
    # grid is (ci_tiles, n) with n innermost; all refs except g carry
    # only this cell's Ci tile, so deep layers' flipped-weight block
    # (9Co x Ci: 4.7 MB untiled at 512x512, double-buffered by Mosaic)
    # stays under the VMEM budget
    n = pl.program_id(1)
    Co = g_ref.shape[-1]
    Ci = x_ref.shape[-1]          # = this cell's Ci tile
    for i in range(NB):
        _fill_patches(gp_scr, pat_scr, i, g_ref[i], H, W, Co, cdt)
    dz = lax.dot_general(                            # (NB*H*W, TCi) f32
        pat_scr[:].reshape(NB * H * W, 9 * Co), wt_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(NB, H, W, Ci)
    s = s_ref[0]
    if relu:
        pre = _act(x_ref[:], s, b_ref[0], False, cdt)
        # compare in f32 — Mosaic has no bf16 vector cmpf
        dpre = dz * (pre.astype(jnp.float32) > 0.0)
    else:
        dpre = dz
    dx_ref[:] = (dpre * s).astype(dx_ref.dtype)

    @pl.when(n == 0)
    def _init():
        ds_ref[:] = jnp.zeros_like(ds_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    ds_ref[:] += jnp.sum(dpre * x_ref[:].astype(jnp.float32),
                         axis=(0, 1, 2))[None]
    db_ref[:] += jnp.sum(dpre, axis=(0, 1, 2))[None]


def _bwd_dx_tiles(N, H, W_, Ci, Co, cbytes):
    """(NB, TCi, fits) for the dx kernel under the ~11 MB VMEM working budget
    (flipped weights + patch scratch dominate; streamed blocks and the
    weight block are double-buffered by Mosaic)."""
    nb = _pick_nb(N, H, W_, Co, cbytes)

    def est(nb_, tci_):
        wt2 = 2 * 9 * Co * tci_ * cbytes
        pat = nb_ * H * W_ * 9 * Co * cbytes
        gp = (H + 2) * (W_ + 2) * Co * cbytes
        blocks = 2 * nb_ * H * W_ * (2 * tci_ + Co) * cbytes
        dz32 = nb_ * H * W_ * tci_ * 4
        return wt2 + pat + gp + blocks + dz32
    return _shrink(nb, Ci, est, _VMEM_BUDGET)


def _bwd_dw_kernel(x_ref, s_ref, b_ref, g_ref, dw_ref, zp_scr, pat_scr, *,
                   NB, H, W, relu, cdt):
    # grid is (co_tiles, n) with n innermost: the (9Ci, TCo) f32
    # accumulator block stays VMEM-resident across the whole batch sweep
    # of one Co tile. Tiling Co keeps deep layers (Ci=Co=512: a 9.4 MB
    # untiled accumulator, double-buffered by Mosaic) under the 16 MB
    # VMEM budget.
    n = pl.program_id(1)
    Ci = x_ref.shape[-1]
    for i in range(NB):
        z = _act(x_ref[i], s_ref[0], b_ref[0], relu, cdt)
        _fill_patches(zp_scr, pat_scr, i, z, H, W, Ci, cdt)
    # single contracting dim over the flattened spatial axis — Mosaic's
    # tpu.matmul rejects multi-dim contractions
    tap = lax.dot_general(                           # (9Ci, TCo) f32
        pat_scr[:].reshape(NB * H * W, 9 * Ci),
        g_ref[:].astype(cdt).reshape(NB * H * W, g_ref.shape[-1]),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(n == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:] += tap


# imported lazily at kernel-trace time on non-TPU hosts would be cleaner,
# but pallas imports are cheap and the module is part of jax
from jax.experimental import pallas as pl              # noqa: E402
from jax.experimental.pallas import tpu as pltpu       # noqa: E402


# Mosaic's scoped-VMEM accounting runs ~5-6 MB above the sum of block +
# scratch sizes (kernel temporaries, spills, extra buffering observed on
# v5e), so tile choices target this conservative working budget.
_VMEM_BUDGET = 7 * 1024 * 1024


def _pick_nb(N, H, W_, C, cbytes):
    """Images per grid cell: small feature maps (deep stages) batch
    several images so the im2col dot presents >=~400 rows to the MXU
    (7x7 alone is 49 sublane-padded rows); cap the patch buffer ~4 MB."""
    nb = 1
    for cand in (8, 4, 2):
        if (N % cand == 0 and H * W_ * cand <= 1024
                and cand * H * W_ * 9 * C * cbytes <= 4 * 1024 * 1024):
            nb = cand
            break
    return nb


def _shrink(nb, tile, est, budget, nb_first=False):
    """Shared tile-shrink policy: halve until est(nb, tile) fits the
    budget. Backward kernels halve the channel tile first (their
    weight/accumulator blocks dominate); the forward halves
    images-per-cell first (keeps the weight block whole and avoids
    rebuilding the im2col patches per Co tile)."""
    def shrink_tile():
        nonlocal tile
        while tile > 128 and tile % 2 == 0 and est(nb, tile) > budget:
            tile //= 2

    def shrink_nb():
        nonlocal nb
        while nb > 1 and est(nb, tile) > budget:
            nb //= 2

    if nb_first:
        shrink_nb()
        shrink_tile()
    else:
        shrink_tile()
        shrink_nb()
    # the floor is (nb=1, tile=128): past it the estimate can still
    # exceed the budget (huge feature maps with fuse forced on) — the
    # caller must fall back instead of dying at Mosaic compile time
    return nb, tile, est(nb, tile) <= budget


def _fwd_tiles(N, H, W_, Ci, Co, cbytes):
    """(NB, TCo, fits) for the forward kernel. The forward weight block is
    observed NOT to be double-buffered (stage-4 untiled compiles at
    ~10 MB), so it counts once. Unlike backward, NB shrinks FIRST:
    halving images-per-cell keeps the weight block whole and avoids
    rebuilding the im2col patches per Co tile."""
    nb = _pick_nb(N, H, W_, Ci, cbytes)

    def est(nb_, tco_):
        w2 = 9 * Ci * tco_ * cbytes
        pat = nb_ * H * W_ * 9 * Ci * cbytes
        zp = (H + 2) * (W_ + 2) * Ci * cbytes
        blocks = 2 * nb_ * H * W_ * (Ci + tco_) * cbytes
        acc32 = nb_ * H * W_ * tco_ * 4
        return w2 + pat + zp + blocks + acc32

    # forward budget is tighter than _VMEM_BUDGET would suggest at big
    # batch (b256 measured 408 KB over at 11 MB)
    return _shrink(nb, Co, est, 10 * 1024 * 1024, nb_first=True)


def _pallas_forward(x, s, b, w, relu, interpret):
    N, H, W_, Ci = x.shape
    Co = w.shape[-1]
    cdt = _compute_dtype(x.dtype)
    cbytes = jnp.dtype(cdt).itemsize
    NB, tco, _ = _fwd_tiles(N, H, W_, Ci, Co, cbytes)
    w2 = w.reshape(9 * Ci, Co).astype(cdt)
    s2 = s.astype(jnp.float32).reshape(1, Ci)
    b2 = b.astype(jnp.float32).reshape(1, Ci)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, NB=NB, H=H, W=W_, relu=relu,
                          cdt=cdt),
        grid=(Co // tco, N // NB),
        in_specs=[
            pl.BlockSpec((NB, H, W_, Ci), lambda c, n: (n, 0, 0, 0)),
            pl.BlockSpec((1, Ci), lambda c, n: (0, 0)),
            pl.BlockSpec((1, Ci), lambda c, n: (0, 0)),
            pl.BlockSpec((9 * Ci, tco), lambda c, n: (0, c)),
        ],
        out_specs=pl.BlockSpec((NB, H, W_, tco),
                               lambda c, n: (n, 0, 0, c)),
        out_shape=jax.ShapeDtypeStruct((N, H, W_, Co), x.dtype),
        scratch_shapes=[pltpu.VMEM((H + 2, W_ + 2, Ci), cdt),
                        pltpu.VMEM((NB, H, W_, 9 * Ci), cdt)],
        interpret=interpret,
    )(x, s2, b2, w2)


def _bwd_dw_tiles(N, H, W_, Ci, Co, cbytes):
    """(NB, TCo, fits) for the d-weight kernel under _VMEM_BUDGET. The f32
    accumulator output block is double-buffered by Mosaic even though
    it is revisited (observed: 2x the block size on the VMEM stack), so
    it counts twice."""
    nb = _pick_nb(N, H, W_, Ci, cbytes)

    def est(nb_, tco_):
        return (nb_ * H * W_ * 9 * Ci * cbytes
                + (H + 2) * (W_ + 2) * Ci * cbytes
                + 2 * nb_ * H * W_ * (Ci + Co) * cbytes
                + 2 * 9 * Ci * tco_ * 4)

    return _shrink(nb, Co, est, _VMEM_BUDGET)


def _pallas_backward(x, s, b, w, relu, interpret, g):
    N, H, W_, Ci = x.shape
    Co = w.shape[-1]
    cdt = _compute_dtype(x.dtype)
    cbytes = jnp.dtype(cdt).itemsize
    s2 = s.astype(jnp.float32).reshape(1, Ci)
    b2 = b.astype(jnp.float32).reshape(1, Ci)
    # d-input: contract shifted dy patches with flipped-transposed taps
    NBx, tci, _ = _bwd_dx_tiles(N, H, W_, Ci, Co, cbytes)
    wt = w[::-1, ::-1].transpose(0, 1, 3, 2).reshape(9 * Co, Ci).astype(cdt)
    dx, ds, db = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, NB=NBx, H=H, W=W_, relu=relu,
                          cdt=cdt),
        grid=(Ci // tci, N // NBx),
        in_specs=[
            pl.BlockSpec((NBx, H, W_, tci), lambda c, n: (n, 0, 0, c)),
            pl.BlockSpec((1, tci), lambda c, n: (0, c)),
            pl.BlockSpec((1, tci), lambda c, n: (0, c)),
            pl.BlockSpec((9 * Co, tci), lambda c, n: (0, c)),
            pl.BlockSpec((NBx, H, W_, Co), lambda c, n: (n, 0, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((NBx, H, W_, tci), lambda c, n: (n, 0, 0, c)),
            pl.BlockSpec((1, tci), lambda c, n: (0, c)),
            pl.BlockSpec((1, tci), lambda c, n: (0, c)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((N, H, W_, Ci), x.dtype),
            jax.ShapeDtypeStruct((1, Ci), jnp.float32),
            jax.ShapeDtypeStruct((1, Ci), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((H + 2, W_ + 2, Co), cdt),
                        pltpu.VMEM((NBx, H, W_, 9 * Co), cdt)],
        interpret=interpret,
    )(x, s2, b2, wt, g)
    # d-weight: accumulate (9Ci, TCo) across the sequential batch grid,
    # Co-tiled so the f32 accumulator + im2col scratch stay under VMEM.
    NBw, tco, _ = _bwd_dw_tiles(N, H, W_, Ci, Co, cbytes)
    w2 = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, NB=NBw, H=H, W=W_, relu=relu,
                          cdt=cdt),
        grid=(Co // tco, N // NBw),
        in_specs=[
            pl.BlockSpec((NBw, H, W_, Ci), lambda c, n: (n, 0, 0, 0)),
            pl.BlockSpec((1, Ci), lambda c, n: (0, 0)),
            pl.BlockSpec((1, Ci), lambda c, n: (0, 0)),
            pl.BlockSpec((NBw, H, W_, tco), lambda c, n: (n, 0, 0, c)),
        ],
        out_specs=pl.BlockSpec((9 * Ci, tco), lambda c, n: (0, c)),
        out_shape=jax.ShapeDtypeStruct((9 * Ci, Co), jnp.float32),
        scratch_shapes=[pltpu.VMEM((H + 2, W_ + 2, Ci), cdt),
                        pltpu.VMEM((NBw, H, W_, 9 * Ci), cdt)],
        interpret=interpret,
    )(x, s2, b2, g)
    dw = w2.reshape(3, 3, Ci, Co).astype(w.dtype)
    return (dx, ds.reshape(Ci).astype(s.dtype),
            db.reshape(Ci).astype(b.dtype), dw)


def _use_pallas(x=None):
    if _getenv("MXTPU_NO_PALLAS", "0") == "1":
        return False
    # a CONCRETE array knows where it lives — eager ops on host-committed
    # arrays (default-ctx cpu NDArrays on a TPU-attached process) must
    # take the reference path even though the default platform is tpu
    if x is not None and isinstance(x, jax.Array):
        try:
            return next(iter(x.devices())).platform == "tpu"
        except Exception:
            pass
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # uninitialized backend etc.
        return False


def _fwd_fits(x, w):
    """True when the forward kernel's shrunk (nb, tile) fits its VMEM
    budget. Reachable to FAIL with fuse=True/pallas_all forced on large
    feature maps; launching anyway would die at Mosaic compile time, so
    the dispatcher falls back to fused_conv_reference instead."""
    N, H, W_, Ci = x.shape
    Co = w.shape[-1]
    cbytes = jnp.dtype(_compute_dtype(x.dtype)).itemsize
    return _fwd_tiles(N, H, W_, Ci, Co, cbytes)[2]


def _bwd_fits(x, w):
    """Same gate for the two backward kernels (their budgets are
    tighter than the forward's, so they are checked separately — a
    forward-only workload keeps the fast kernel either way)."""
    N, H, W_, Ci = x.shape
    Co = w.shape[-1]
    cbytes = jnp.dtype(_compute_dtype(x.dtype)).itemsize
    return (_bwd_dx_tiles(N, H, W_, Ci, Co, cbytes)[2]
            and _bwd_dw_tiles(N, H, W_, Ci, Co, cbytes)[2])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused(x, s, b, w, relu, interpret):
    # forward gates on the FORWARD plan only: an inference-only call
    # must not lose the fast kernel because a backward plan (checked in
    # _fused_bwd) would not fit
    if interpret or (_use_pallas(x) and _fwd_fits(x, w)):
        return _pallas_forward(x, s, b, w, relu, interpret)
    return fused_conv_reference(x, s, b, w, relu)


def _fused_fwd(x, s, b, w, relu, interpret):
    return _fused(x, s, b, w, relu, interpret), (x, s, b, w)


def _fused_bwd(relu, interpret, res, g):
    x, s, b, w = res
    if interpret or (_use_pallas(x) and _bwd_fits(x, w)):
        return _pallas_backward(x, s, b, w, relu, interpret, g)
    _, vjp = jax.vjp(
        lambda x_, s_, b_, w_: fused_conv_reference(x_, s_, b_, w_, relu),
        x, s, b, w)
    return vjp(g)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_scale_relu_conv3x3(x, s, b, w, relu=True, interpret=False):
    """conv3x3(relu(x*s + b), w) with the normalize/ReLU chain fused into
    the conv's VMEM operand load (never materialized in HBM).

    x: (N, H, W, Ci) NHWC; s, b: (Ci,); w: (3, 3, Ci, Co) HWIO.
    Stride 1, SAME padding. Falls back to an identical-semantics jnp
    reference off-TPU. ``relu=False`` gives conv3x3(x*s + b, w).
    """
    if x.ndim != 4 or w.shape[:2] != (3, 3) or w.shape[2] != x.shape[-1]:
        raise ValueError("fused_scale_relu_conv3x3: need NHWC x and "
                         "(3,3,Ci,Co) w, got %s / %s"
                         % (x.shape, w.shape))
    return _fused(x, s, b, w, bool(relu), bool(interpret))
