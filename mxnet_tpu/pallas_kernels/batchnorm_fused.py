"""Fused training-mode BatchNorm (stats + normalize + optional
activation) as Pallas TPU kernels.

Why this kernel exists: BENCH_r05's worst non-matmul numerics outlier
is BatchNorm (11,482 ULP vs the CPU golden) and the XLA lowering of the
fallback materializes the activation between the stat reduction and the
normalize. This kernel computes the whole training-mode BN

    mean, var = moments(x)           # f32 accumulation, deterministic
    y = (x - mean) / sqrt(var + eps) * gamma + beta
    out = act(y)                     # optional relu, fused

with every intermediate held in VMEM in f32:

- ``stats`` kernel: grid (channel tiles, row tiles) with the row sweep
  innermost; each cell folds its (TR, TC) block with the deterministic
  pairwise tree (``tree_fold_rows``) and accumulates sum/sum-of-squares
  partials into a VMEM-resident f32 block (the conv_fused d-weight
  accumulation pattern), converting to mean/var on the last row tile.
  Single-pass E[x^2]-E[x]^2 in f32 with a >=0 clamp: the cancellation
  term is ~mean^2 * 2^-24, negligible against every reachable eps.
- ``apply`` kernel: elementwise normalize + optional relu over the same
  tiling, reading the (1, C) stats once per channel tile. The
  activation never exists unnormalized in HBM.
- backward: two kernels in the same shape — a reduce kernel
  accumulating dbeta/dgamma (recomputing xhat and the relu mask in
  VMEM) and an elementwise d-input kernel applying the standard
  batch-stat backward ``dx = gamma*inv*(dy' - E[dy'] - xhat*E[dy'*xhat])``.

Numerics contract: stats accumulate in f32 regardless of input dtype
and the normalize chain is correctly-rounded primitives only
(sub/mul/add, ``1/sqrt`` instead of the approximate ``lax.rsqrt``), so
kernel-vs-reference parity is ULP-bounded (gated in
``BENCH_MODEL=fused_kernels`` and tests/test_pallas_kernels.py).
``ops/nn.py:batch_norm`` routes its training-mode, channels-last path
here on TPU (``MXTPU_FUSED_BN``; ``use_global_stats`` / inference and
non-trailing-axis layouts keep the XLA fallback, whose stats share the
same deterministic ``tree_fold_rows``). Moving-stat updates stay with
the caller (gluon layer), exactly as for the fallback.

The reference's analog is the fused BatchNorm+activation CUDA path
(ref: src/operator/nn/batch_norm.cu + cudnn_batch_norm); the TPU-native
design additionally pins the reduction ORDER so CPU goldens and device
runs agree to a few ULP.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ._compile_attr import attributed
from .conv_fused import _use_pallas
from ..base import getenv as _getenv

__all__ = ["fused_batch_norm", "batchnorm_reference", "tree_fold_rows",
           "engaged"]

_ENV = "MXTPU_FUSED_BN"


def _setting():
    return _getenv(_ENV, "1")


def _force_interpret():
    return _setting() == "interpret"


# The deterministic reduction, in three composable pieces. The shape of
# the algorithm is chosen so the Pallas kernel's tiling DECOMPOSES the
# reference tree exactly: ``fold_blocks`` sums fixed 64-row blocks with
# a contiguous-halves tree (any row tile that is a multiple of 64
# produces the identical per-block partials), ``fold_partials`` folds
# the per-block partials with the same tree, and the whole pipeline
# contains only f32 ADDS over already-rounded values — the one
# reduction shape that is bitwise-reproducible across platforms,
# fusion contexts, and tilings (a mul feeding an add would get
# FMA-contracted differently per compiled program; see ``exact_sq``
# for how the variance path neutralizes that too).

FOLD_BLOCK = 64


def _fold_pow2(v, axis):
    """Contiguous-halves fold of a power-of-two axis down to length 1."""
    p = v.shape[axis]
    while p > 1:
        p //= 2
        lo = jax.lax.slice_in_dim(v, 0, p, axis=axis)
        hi = jax.lax.slice_in_dim(v, p, 2 * p, axis=axis)
        v = lo + hi
    return v


def fold_blocks(v):
    """(R, C) -> (ceil(R/64), C): per-64-row-block column sums, each
    block folded by a contiguous-halves tree. Rows pad to a block
    multiple with exact zeros. Runs identically as XLA ops and inside
    a Mosaic kernel (static leading-dim reshape + sublane slicing)."""
    n, c = v.shape
    nb = -(-n // FOLD_BLOCK)
    if nb * FOLD_BLOCK != n:
        v = jnp.concatenate(
            [v, jnp.zeros((nb * FOLD_BLOCK - n, c), v.dtype)], axis=0)
    return _fold_pow2(v.reshape(nb, FOLD_BLOCK, c), 1).reshape(nb, c)


def fold_partials(parts):
    """(NB, C) block partials -> (1, C) total, padding NB to the next
    power of two with exact zeros and folding contiguous halves."""
    n = parts.shape[0]
    p = 1
    while p < n:
        p *= 2
    if p != n:
        parts = jnp.concatenate(
            [parts, jnp.zeros((p - n,) + parts.shape[1:], parts.dtype)],
            axis=0)
    return _fold_pow2(parts, 0)


def tree_fold_rows(v):
    """Deterministic column sum: (R, C) -> (1, C), f32 in f32 out.
    ``fold_partials(fold_blocks(v))`` — every platform and every
    fusion context executes the SAME sequence of correctly-rounded f32
    adds, so CPU goldens, TPU runs, and the Pallas kernel's tiled
    partials produce bitwise-identical sums. The property the
    BatchNorm stats (and the per-op ULP gate in
    benchmark/tpu_numerics.py, budget 64) rest on."""
    return fold_partials(fold_blocks(v))


def exact_sq(x):
    """x^2 by exact-product splitting, immune to FMA contraction.

    LLVM/Mosaic may contract ``t = x*x`` feeding an add into an FMA —
    a choice that differs per compiled program, which would make any
    sum of squares context-dependent in the last bit. Split x by
    mantissa masking (pure bit ops) into xh + xl with <=12 significant
    bits each: xh^2, 2*xh*xl and xl^2 are then EXACTLY representable
    f32 products, and contracting an exact product into an add is a
    rounding no-op — so ``xh^2 + (2*xh*xl + xl^2)`` is deterministic
    everywhere (and slightly MORE accurate than round(x*x))."""
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    xh = jax.lax.bitcast_convert_type(
        bits & jnp.int32(-4096), jnp.float32)  # keep top 11 mantissa bits
    xl = x - xh
    t = xh * xh + (2.0 * (xh * xl) + xl * xl)
    # inf: xl = inf - inf = nan; mirror plain x*x for non-finite inputs
    return jnp.where(jnp.isfinite(x), t, x * x)


def exact_mul(a, b):
    """a*b by the same exact-product splitting as ``exact_sq`` —
    deterministic under any FMA contraction choice, and the building
    block that makes the whole BN normalize chain bitwise-reproducible:
    ``exact_mul(x - mean, inv*gamma) + beta`` ends in an add whose
    multiply operand is already rounded, so no backend can contract it
    differently."""
    abits = jax.lax.bitcast_convert_type(a, jnp.int32)
    bbits = jax.lax.bitcast_convert_type(b, jnp.int32)
    ah = jax.lax.bitcast_convert_type(abits & jnp.int32(-4096),
                                      jnp.float32)
    bh = jax.lax.bitcast_convert_type(bbits & jnp.int32(-4096),
                                      jnp.float32)
    al, bl = a - ah, b - bh
    t = ah * bh + (ah * bl + (al * bh + al * bl))
    return jnp.where(jnp.isfinite(a) & jnp.isfinite(b), t, a * b)


def batchnorm_reference(x, gamma, beta, eps=1e-3, act=None):
    """jnp semantics of the fused op (fallback + autodiff + goldens).

    x: (..., C) channels-last; gamma, beta: (C,).
    Returns (out[x.dtype], mean32, var32) with (C,) f32 stats. The stat
    math is the kernel's exactly: deterministic tree-fold sums, f32
    single-pass variance clamped at 0, ``1/sqrt`` normalize.
    """
    C = x.shape[-1]
    x2 = x.reshape(-1, C)
    R = x2.shape[0]
    xf = x2.astype(jnp.float32)
    mean = tree_fold_rows(xf)[0] / R
    var = jnp.maximum(
        tree_fold_rows(exact_sq(xf))[0] / R - exact_sq(mean), 0.0)
    inv = 1.0 / jnp.sqrt(var + eps)
    y = exact_mul(xf - mean, inv * gamma.astype(jnp.float32)) \
        + beta.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype).reshape(x.shape), mean, var


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

from jax.experimental import pallas as pl                # noqa: E402

# same conservative working budget as conv_fused (Mosaic's scoped-VMEM
# accounting runs a few MB above the block-size sum)
_VMEM_BUDGET = 7 * 1024 * 1024


def _stats_kernel(x_ref, sum_ref, sq_ref):
    # per-block partial sums only: the cross-tile combination happens
    # in the wrapper with fold_partials, so the kernel's tiling
    # reproduces the reference tree EXACTLY (tiles are multiples of
    # FOLD_BLOCK, and fold_blocks of a tile == that tile's slice of
    # fold_blocks over the full array)
    xf = x_ref[:].astype(jnp.float32)
    sum_ref[:] = fold_blocks(xf)
    sq_ref[:] = fold_blocks(exact_sq(xf))


def _apply_kernel(x_ref, g_ref, b_ref, mean_ref, var_ref, o_ref, *,
                  eps, act):
    inv = 1.0 / jnp.sqrt(var_ref[:] + eps)
    y = exact_mul(x_ref[:].astype(jnp.float32) - mean_ref[:],
                  inv * g_ref[:].astype(jnp.float32)) \
        + b_ref[:].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    o_ref[:] = y.astype(o_ref.dtype)


def _bwd_reduce_kernel(x_ref, g_ref, b_ref, mean_ref, var_ref, dy_ref,
                       db_ref, dg_ref, *, eps, act):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        db_ref[:] = jnp.zeros_like(db_ref)
        dg_ref[:] = jnp.zeros_like(dg_ref)

    inv = 1.0 / jnp.sqrt(var_ref[:] + eps)
    xhat = (x_ref[:].astype(jnp.float32) - mean_ref[:]) * inv
    dyf = dy_ref[:].astype(jnp.float32)
    if act == "relu":
        y = xhat * g_ref[:].astype(jnp.float32) \
            + b_ref[:].astype(jnp.float32)
        dyf = dyf * (y > 0.0)
    db_ref[:] += tree_fold_rows(dyf)
    dg_ref[:] += tree_fold_rows(dyf * xhat)


def _bwd_dx_kernel(x_ref, g_ref, b_ref, mean_ref, var_ref, dy_ref,
                   db_ref, dg_ref, dx_ref, *, R, eps, act):
    inv = 1.0 / jnp.sqrt(var_ref[:] + eps)
    g32 = g_ref[:].astype(jnp.float32)
    xhat = (x_ref[:].astype(jnp.float32) - mean_ref[:]) * inv
    dyf = dy_ref[:].astype(jnp.float32)
    if act == "relu":
        y = xhat * g32 + b_ref[:].astype(jnp.float32)
        dyf = dyf * (y > 0.0)
    dx = g32 * inv * (dyf - db_ref[:] / R - xhat * (dg_ref[:] / R))
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _tiles(R, C, xbytes, n_blocks):
    """(TR, TC, fits): row/channel tile so ``n_blocks`` streamed
    (TR, TC) blocks (double-buffered) plus their f32 working copies fit
    the VMEM budget. Row tiles are power-of-two multiples of
    FOLD_BLOCK so each tile's ``fold_blocks`` partials are exactly the
    reference tree's; the real-TPU path additionally requires
    lane-aligned channels (C % 128) and an exact row tiling."""
    tc = C
    tr = 1024

    def est(tr_, tc_):
        return tr_ * tc_ * (2 * n_blocks * xbytes + (n_blocks + 2) * 4)

    while tc > 128 and tc % 2 == 0 and est(min(tr, R), tc) > _VMEM_BUDGET:
        tc //= 2
    while tr > FOLD_BLOCK and (tr > R or R % tr != 0
                               or est(tr, tc) > _VMEM_BUDGET):
        tr //= 2
    fits = (C % 128 == 0 and C % tc == 0 and R % tr == 0
            and est(tr, tc) <= _VMEM_BUDGET)
    return tr, tc, fits


def _fwd_fits(x2):
    R, C = x2.shape
    return _tiles(R, C, jnp.dtype(x2.dtype).itemsize, 2)[2]


def _bwd_fits(x2):
    R, C = x2.shape
    return _tiles(R, C, jnp.dtype(x2.dtype).itemsize, 3)[2]


def _pallas_forward(x2, gamma, beta, eps, act, interpret):
    R, C = x2.shape
    xbytes = jnp.dtype(x2.dtype).itemsize
    TR, TC, _ = _tiles(R, C, xbytes, 2)
    if interpret and R % TR:
        TR = R  # single row tile: no divisibility constraints on CPU
    nr = pl.cdiv(R, TR)
    key = (R, C, str(x2.dtype), act)
    pt = -(-TR // FOLD_BLOCK)  # per-tile partial rows
    sums, sqs = attributed("batchnorm_fused.stats", key, lambda:
        pl.pallas_call(
            _stats_kernel,
            grid=(C // TC, nr),
            in_specs=[pl.BlockSpec((TR, TC), lambda c, r: (r, c))],
            out_specs=(pl.BlockSpec((pt, TC), lambda c, r: (r, c)),
                       pl.BlockSpec((pt, TC), lambda c, r: (r, c))),
            out_shape=(jax.ShapeDtypeStruct((nr * pt, C), jnp.float32),
                       jax.ShapeDtypeStruct((nr * pt, C), jnp.float32)),
            interpret=interpret,
        )(x2))
    # finish the tree outside: fold_partials over the per-block sums is
    # bitwise the reference's tree_fold_rows (tile edges sit on
    # FOLD_BLOCK boundaries), so kernel stats == reference stats
    mean = fold_partials(sums) / R
    var = jnp.maximum(fold_partials(sqs) / R - exact_sq(mean), 0.0)
    g2 = gamma.astype(jnp.float32).reshape(1, C)
    b2 = beta.astype(jnp.float32).reshape(1, C)
    out = attributed("batchnorm_fused.apply", key, lambda:
        pl.pallas_call(
            functools.partial(_apply_kernel, eps=eps, act=act),
            grid=(C // TC, nr),
            in_specs=[
                pl.BlockSpec((TR, TC), lambda c, r: (r, c)),
                pl.BlockSpec((1, TC), lambda c, r: (0, c)),
                pl.BlockSpec((1, TC), lambda c, r: (0, c)),
                pl.BlockSpec((1, TC), lambda c, r: (0, c)),
                pl.BlockSpec((1, TC), lambda c, r: (0, c)),
            ],
            out_specs=pl.BlockSpec((TR, TC), lambda c, r: (r, c)),
            out_shape=jax.ShapeDtypeStruct((R, C), x2.dtype),
            interpret=interpret,
        )(x2, g2, b2, mean, var))
    return out, mean.reshape(C), var.reshape(C)


def _pallas_backward(x2, gamma, beta, mean, var, dy2, eps, act,
                     interpret):
    R, C = x2.shape
    xbytes = jnp.dtype(x2.dtype).itemsize
    TR, TC, _ = _tiles(R, C, xbytes, 3)
    if interpret and R % TR:
        TR = R  # single row tile: no divisibility constraints on CPU
    nr = pl.cdiv(R, TR)
    key = (R, C, str(x2.dtype), act)
    g2 = gamma.astype(jnp.float32).reshape(1, C)
    b2 = beta.astype(jnp.float32).reshape(1, C)
    m2 = mean.reshape(1, C)
    v2 = var.reshape(1, C)
    stat_spec = pl.BlockSpec((1, TC), lambda c, r: (0, c))
    blk_spec = pl.BlockSpec((TR, TC), lambda c, r: (r, c))
    db, dg = attributed("batchnorm_fused.bwd_reduce", key, lambda:
        pl.pallas_call(
            functools.partial(_bwd_reduce_kernel, eps=eps, act=act),
            grid=(C // TC, nr),
            in_specs=[blk_spec, stat_spec, stat_spec, stat_spec,
                      stat_spec, blk_spec],
            out_specs=(stat_spec, stat_spec),
            out_shape=(jax.ShapeDtypeStruct((1, C), jnp.float32),
                       jax.ShapeDtypeStruct((1, C), jnp.float32)),
            interpret=interpret,
        )(x2, g2, b2, m2, v2, dy2))
    dx = attributed("batchnorm_fused.bwd_dx", key, lambda:
        pl.pallas_call(
            functools.partial(_bwd_dx_kernel, R=R, eps=eps, act=act),
            grid=(C // TC, nr),
            in_specs=[blk_spec, stat_spec, stat_spec, stat_spec,
                      stat_spec, blk_spec, stat_spec, stat_spec],
            out_specs=blk_spec,
            out_shape=jax.ShapeDtypeStruct((R, C), x2.dtype),
            interpret=interpret,
        )(x2, g2, b2, m2, v2, dy2, db, dg))
    return dx, dg.reshape(C).astype(gamma.dtype), \
        db.reshape(C).astype(beta.dtype)


# ---------------------------------------------------------------------------
# custom_vjp dispatcher
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused(x, gamma, beta, eps, act, interpret):
    if interpret or (_use_pallas(x) and _fwd_fits(
            x.reshape(-1, x.shape[-1]))):
        C = x.shape[-1]
        out2, mean, var = _pallas_forward(x.reshape(-1, C), gamma, beta,
                                          eps, act, interpret)
        return out2.reshape(x.shape), mean, var
    return batchnorm_reference(x, gamma, beta, eps, act)


def _fused_fwd(x, gamma, beta, eps, act, interpret):
    out, mean, var = _fused(x, gamma, beta, eps, act, interpret)
    return (out, mean, var), (x, gamma, beta, mean, var)


def _fused_bwd(eps, act, interpret, res, cts):
    x, gamma, beta, mean, var = res
    dy, gmean, gvar = cts
    C = x.shape[-1]
    x2 = x.reshape(-1, C)
    R = x2.shape[0]
    if interpret or (_use_pallas(x) and _bwd_fits(x2)):
        dx2, dgamma, dbeta = _pallas_backward(
            x2, gamma, beta, mean, var, dy.reshape(-1, C), eps, act,
            interpret)
        dx = dx2.reshape(x.shape)
    else:
        _, vjp = jax.vjp(
            lambda x_, g_, b_: batchnorm_reference(x_, g_, b_, eps,
                                                   act)[0], x, gamma,
            beta)
        dx, dgamma, dbeta = vjp(dy)
    # cotangents of the stat OUTPUTS (zero in every training loop — the
    # moving-stat update happens outside autograd — but a caller
    # differentiating through mean/var must still get the d mean/dx =
    # 1/R and d var/dx = 2(x-mean)/R terms)
    stat_ct = (gmean + 2.0 * (x2.astype(jnp.float32) - mean) * gvar) / R
    dx = dx + stat_ct.reshape(x.shape).astype(x.dtype)
    return dx, dgamma, dbeta


_fused.defvjp(_fused_fwd, _fused_bwd)


def engaged(x, axis):
    """Whether ops/nn.py:batch_norm should take the kernel for this
    training-mode call: enabled, channels-last, and either on TPU with
    a fitting plan or force-interpreted (``MXTPU_FUSED_BN=interpret``,
    the CPU test hook)."""
    if _setting() == "0" or x.ndim < 2 or axis != x.ndim - 1:
        return False
    if _force_interpret():
        return True
    R = 1
    for s in x.shape[:-1]:
        R *= int(s)
    fake = jax.ShapeDtypeStruct((R, x.shape[-1]), x.dtype)
    return _use_pallas(x) and _fwd_fits(fake) and _bwd_fits(fake)


def fused_batch_norm(x, gamma, beta, eps=1e-3, act=None,
                     interpret=False):
    """Training-mode BatchNorm over the trailing axis with fused stats,
    normalize, and optional activation (``act=None|'relu'``).

    x: (..., C) channels-last; gamma, beta: (C,). Returns
    ``(out, mean, var)`` with f32 (C,) batch stats — moving-average
    updates belong to the caller, matching ``ops/nn.py:batch_norm``.
    Falls back to ``batchnorm_reference`` (identical semantics) off-TPU
    or when the tiling does not fit VMEM; ``interpret=True`` runs the
    Pallas kernels in interpreter mode for CPU tests.
    """
    if x.ndim < 2 or gamma.shape != (x.shape[-1],) \
            or beta.shape != (x.shape[-1],):
        raise ValueError("fused_batch_norm: need (..., C) x and (C,) "
                         "gamma/beta, got %s / %s / %s"
                         % (x.shape, gamma.shape, beta.shape))
    if act not in (None, "relu"):
        raise ValueError("fused_batch_norm: act must be None or 'relu', "
                         "got %r" % (act,))
    interpret = bool(interpret) or _force_interpret()
    return _fused(x, gamma, beta, float(eps), act, interpret)
