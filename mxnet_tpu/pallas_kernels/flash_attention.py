"""Flash attention: tiled online-softmax attention as a Pallas TPU kernel.

The reference has no attention kernel at all (2019-era; its closest analog
is the fused cuDNN RNN, src/operator/rnn-inl.h). Long-context attention is
where a modern framework's FLOPs go, so this is the flagship custom
kernel: per (batch*head, q-block) grid cell, K/V stream through VMEM in
``block_k`` tiles while the m/l/o running softmax accumulates in
registers — HBM traffic is O(S·D) instead of the O(S^2) score matrix.

Composition with the parallelism layer: ring attention
(parallel/ring_attention.py) shards the sequence over the mesh and
rotates K/V via ppermute; each hop's local block product can use this
kernel, making the two-level scheme (inter-chip ring x intra-chip flash)
match Liu et al.'s blockwise formulation.

Backward is a pair of Pallas kernels in the flash-2 formulation: the
forward saves only the per-row logsumexp L = m + log(l) (O(S) extra);
the backward recomputes each (block_q, block_k) score tile inside the
kernel from Q/K/L, so dQ/dK/dV are produced with O(S*D) HBM traffic and
O(block^2) VMEM — the O(S^2) score matrix is never materialized in
either direction. On non-TPU backends (and when the kernel is bypassed)
the jnp reference's XLA vjp is used instead.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ..base import getenv as _getenv

__all__ = ["flash_attention", "attention_reference"]

# Mosaic requires the minor block dim to be a multiple of 128 lanes, so
# per-row scalars (logsumexp, delta) are stored broadcast over 128 lanes.
_LANES = 128


def _fit_block(requested, size, quantum):
    """Largest block <= requested that divides `size` and is a multiple of
    `quantum` (Mosaic sublane/lane granularity). Falls back to `size`
    itself (one block spanning the axis) when no such divisor exists —
    but only while that still fits VMEM: for e.g. a prime seq length the
    whole-axis block would allocate a size^2 fp32 score tile and die in
    an opaque Mosaic compile error, so raise actionable padding guidance
    instead."""
    b = min(requested, size)
    if size % b == 0:
        return b
    b = (b // quantum) * quantum
    while b >= quantum:
        if size % b == 0:
            return b
        b -= quantum
    if size > 4 * max(requested, quantum):
        raise ValueError(
            "flash_attention: sequence length %d has no block divisor that "
            "is a multiple of %d; pad the sequence to a multiple of %d "
            "(e.g. with jnp.pad + masking) or pass a block size that "
            "divides it" % (size, quantum, quantum))
    return size


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain O(S^2) attention in jnp — fallback + autodiff path.
    q,k,v: [B, H, S, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # scores + softmax in fp32 regardless of input dtype — same as the
    # Pallas kernel's accumulators, so the two paths agree under AMP bf16
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row, -jnp.inf, s)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype),
                      v).astype(q.dtype)


def _causal_dispatch(qi, ki, block_q, block_k, compute):
    """Run ``compute(masked)`` for one (q-block, k-block) causal cell:
    blocks strictly above the diagonal are skipped, diagonal-straddling
    blocks run masked, strictly-below blocks run unmasked. Shared by the
    forward and both backward kernels so the classification cannot
    drift."""
    import jax.experimental.pallas as pl

    below = ki * block_k + block_k - 1 <= qi * block_q

    @pl.when(jnp.logical_and(
        ki * block_k <= qi * block_q + block_q - 1,
        jnp.logical_not(below)))
    def _():
        compute(True)

    @pl.when(below)
    def _():
        compute(False)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, o_scr, *,
                block_q, block_k, causal, scale, n_kblocks):
    """One (batch*head, q-block, k-block) grid cell. The TPU grid runs
    sequentially with the k axis innermost, so VMEM scratch carries the
    m/l/o online-softmax state across k steps — only one (block_k, D)
    K/V tile is resident at a time, keeping VMEM O(block) instead of
    O(seq)."""
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        o_scr[:] = jnp.zeros_like(o_scr)

    def compute(masked):
        # dots run on the input dtype (bf16 hits the MXU at full rate;
        # f32 would be 8x slower) and accumulate in f32.
        # No isneginf guards: every q row's FIRST processed block (ki=0)
        # contains its valid col 0, so m stays finite from the first
        # step on, exp(-inf - finite) underflows to exactly 0 for both
        # masked scores and the m_prev=-inf init, and no exp(-inf+inf)
        # NaN can form. (Fully-masked rows cannot occur: causal row r
        # always sees cols 0..r.)
        q = q_ref[0]                                  # (block_q, D)
        k = k_ref[0]                                  # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if masked:
            row = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, -jnp.inf, s)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = corr * l_scr[:, 0] + jnp.sum(p, axis=-1)
        o_scr[:] = corr[:, None] * o_scr[:] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # most active blocks at long seq are strictly below the diagonal
        # and skip the per-element iota/compare/select VPU work
        _causal_dispatch(qi, ki, block_q, block_k, compute)
    else:
        compute(False)

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        # INVARIANT: no row is ever fully masked (causal row r sees cols
        # 0..r; non-causal sees everything; ring x flash skips
        # fully-masked hops before calling the kernel), so l > 0 and
        # lse is finite — the backward recompute relies on this.
        # Broadcast across a 128-lane minor dim — Mosaic requires the
        # last block dim to be a multiple of 128, so scalars-per-row
        # ride a full lane register.
        l = l_scr[:, 0]
        lse = m_scr[:, 0] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])
        o_ref[0] = (o_scr[:] / l[:, None]).astype(o_ref.dtype)


def _pallas_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(block_q, sq, 8)
    block_k = _fit_block(block_k, sk, 128)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    n_kblocks = sk // block_k
    kernel = functools.partial(_fwd_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               n_kblocks=n_kblocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),    # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),    # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),    # unnormalized output
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    qi, ki, block_q, block_k, masked, scale):
    """Shared flash-2 backward recompute: rebuild the (block_q, block_k)
    probability tile from Q/K and the saved row logsumexp, then
    dS = P * (dP - delta) * scale. Used by both _dq_kernel and
    _dkv_kernel so the masking/lse-safety logic cannot drift.
    ``masked`` is static: only diagonal-straddling blocks pay the iota
    mask; masked scores give p = exp(-inf - lse) = 0 exactly (causal
    rows always have a finite lse — see _fwd_kernel)."""
    q = q_ref[0]                                  # (block_q, D)
    k = k_ref[0]                                  # (block_k, D)
    v = v_ref[0]
    do = do_ref[0]                                # (block_q, D)
    lse = lse_ref[0][:, 0]                        # (block_q,)
    delta = delta_ref[0][:, 0]                    # (block_q,)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if masked:
        row = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(col > row, -jnp.inf, s)
    p = jnp.exp(s - lse[:, None])                 # (block_q, block_k)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)       # (block_q, block_k)
    ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, block_q, block_k, causal, scale, n_kblocks):
    """dQ for one (batch*head, q-block) cell; k innermost.
    dQ += dS @ K."""
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute(masked):
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, qi, ki, block_q, block_k,
                                masked, scale)
        dq_scr[:] += jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        _causal_dispatch(qi, ki, block_q, block_k, compute)
    else:
        compute(False)

    @pl.when(ki == n_kblocks - 1)
    def _write():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, block_q, block_k,
                causal, scale, n_qblocks):
    """dK/dV for one (batch*head, k-block) cell; q innermost.
    dV += P^T @ dO; dK += dS^T @ Q."""
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute(masked):
        do = do_ref[0]
        p, ds = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, qi, ki, block_q, block_k,
                                masked, scale)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_k, D)
        dk_scr[:] += jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_k, D)

    if causal:
        _causal_dispatch(qi, ki, block_q, block_k, compute)
    else:
        compute(False)

    @pl.when(qi == n_qblocks - 1)
    def _write():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                     interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(block_q, sq, 8)
    block_k = _fit_block(block_k, sk, 128)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    dof = g.reshape(b * h, sq, d)
    # the O(S) per-row residual/correction vectors ride a 128-lane minor
    # dim only here, transiently, for the Mosaic block constraint — the
    # saved residual itself is (bh, sq)
    lse = jnp.broadcast_to(lse[:, :, None], (b * h, sq, _LANES))
    # delta_i = sum_d dO_i * O_i — the rowwise correction in dS; O(S*D)
    delta = jnp.broadcast_to(
        jnp.sum(dof.astype(jnp.float32)
                * o.reshape(b * h, sq, d).astype(jnp.float32),
                axis=-1, keepdims=True), (b * h, sq, _LANES))
    n_qblocks = sq // block_q
    n_kblocks = sk // block_k

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale, n_kblocks=n_kblocks),
        grid=(b * h, n_qblocks, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale, n_qblocks=n_qblocks),
        grid=(b * h, n_kblocks, n_qblocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, j, i: (bh, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    if interpret or _use_pallas():
        return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)[0]
    return attention_reference(q, k, v, causal=causal, scale=scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if interpret or _use_pallas():
        out, lse = _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
        # keep one lane of the (bh, sq, 128) kernel output — the lane dim
        # exists only for Mosaic's block constraint, not worth 128x HBM
        # across the fwd->bwd interval
        return out, (q, k, v, out, lse[:, :, 0])
    out = attention_reference(q, k, v, causal=causal, scale=scale)
    return out, (q, k, v, None, None)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    if lse is None:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal,
                                                   scale=scale), q, k, v)
        return vjp(g)
    return _pallas_backward(q, k, v, o, lse, g, causal, scale, block_q,
                            block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Measured block optima, one v5e chip, causal fwd+bwd (round-3 scans).
# Isolated-kernel winners and in-context (full remat train step) winners
# DIFFER: at seq 2048 the isolated scan prefers (512,512) by 20%, but
# inside the remat'd transformer step (1024,1024) is 2% faster end to
# end — VMEM pressure and recompute scheduling shift the optimum. The
# table holds in-context winners; MXTPU_FLASH_AUTOTUNE=1 searches the
# exact shape (isolated — verify winners in context before pinning).
_BLOCK_TABLE = {
    2048: (1024, 1024),
    4096: (1024, 1024),
    8192: (1024, 1024),
}
_TUNE_CANDIDATES = [(512, 512), (512, 1024), (1024, 512), (1024, 1024),
                    (2048, 512), (256, 512)]
_TUNE_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of measured block sizes; a racing duplicate tune costs time, never correctness)


def _default_blocks(seq):
    if seq in _BLOCK_TABLE:
        return _BLOCK_TABLE[seq]
    if seq <= 2048:
        return (512, 512)
    if seq <= 4096:
        return (1024, 1024)
    return (2048, 512)


def _autotune_blocks(q, k, v, causal, scale):
    """Measure every candidate on the attached device for this exact
    shape and cache the winner (enabled by MXTPU_FLASH_AUTOTUNE=1 —
    the analog of the reference's cuDNN algo search,
    ref: src/operator/nn/cudnn/cudnn_algoreg-inl.h)."""
    import time
    key = (q.shape, causal)
    if key in _TUNE_CACHE:
        return _TUNE_CACHE[key]
    best, best_dt = None, float("inf")
    for bq, bk in _TUNE_CANDIDATES:
        if bq > q.shape[2] or bk > k.shape[2]:
            continue
        try:
            def loss(q_, k_, v_, bq=bq, bk=bk):
                o = _flash(q_, k_, v_, causal, float(scale), bq, bk, False)
                return jnp.sum(o.astype(jnp.float32))
            # grad over ALL inputs so the dk/dv backward kernel is part
            # of what gets timed (grad on q alone would let XLA DCE it)
            grad = jax.grad(loss, argnums=(0, 1, 2))

            @jax.jit  # mxlint: disable=MX005,MX022 (tuning micro-bench: compiled once per candidate block size inside the memoized autotune pass, timed by the autotuner itself)
            def many(q_, k_, v_):
                # chained fori so the device actually serializes the
                # iterations (async dispatch would lie to the timer)
                def body(i, qkv):
                    qq, kk, vv = qkv
                    dq, dk, dv = grad(qq, kk, vv)
                    return (qq + 1e-12 * dq, kk + 1e-12 * dk,
                            vv + 1e-12 * dv)
                return lax.fori_loop(0, 5, body, (q_, k_, v_))[0]

            warm = many(q, k, v)  # compile
            # allocation-ledger choke point (ISSUE 13a): the autotune
            # trial buffers are the 'workspace' tag — the transient HBM
            # spike a tuning pass costs shows up attributed, not as
            # anonymous growth
            from .. import storage as _storage
            _storage.ledger_register(warm, "workspace",
                                     site="flash.autotune")
            float(jnp.sum(warm.astype(jnp.float32)))
            # mxlint: disable=MX014 (host-side autotune timing: the measured winner is memoized per shape and MXTPU_FLASH_AUTOTUNE is a signature token, so timing noise never changes an already-cached executable)
            t0 = time.perf_counter()
            float(jnp.sum(many(q, k, v).astype(jnp.float32)))
            # mxlint: disable=MX014 (host-side autotune timing, see t0 above)
            dt = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — candidate too big for VMEM etc.
            continue
        if dt < best_dt:
            best, best_dt = (bq, bk), dt
    if best is None:
        # nothing ran (all candidates failed) — fall back WITHOUT
        # caching, so a later healthy call can still tune this shape
        return _default_blocks(q.shape[2])
    _TUNE_CACHE[key] = best
    return best


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=False):
    """Tiled attention. q,k,v: [B, H, S, D]. On TPU runs the Pallas
    kernel; elsewhere the jnp reference (or the kernel under
    ``interpret=True`` for testing). block_q/block_k default to the
    measured per-shape optimum (table above; exact-shape search with
    MXTPU_FLASH_AUTOTUNE=1); explicit values override. Blocks clamp to
    the sequence length."""
    import os
    if causal and q.shape[2] != k.shape[2]:
        # This kernel's causal mask is LEFT-aligned (col > row masked),
        # which is only the right semantics when q and kv index the
        # same positions. Decode-style calls (q_len=1 against an
        # N-entry KV cache) need RIGHT-aligned masking and would get
        # silently wrong attention here — reject loudly instead.
        # (A fully-masked row, the other classic hazard, cannot occur
        # under left alignment: row r always sees col 0.) Ring /
        # sequence-parallel callers handle per-hop offsets themselves
        # before calling in (parallel/ring_flash).
        raise ValueError(
            "flash_attention(causal=True) requires equal q/kv lengths "
            "(got %d vs %d): the causal mask is left-aligned, so "
            "decode-style q-against-longer-kv calls would be silently "
            "mis-masked; use attention_reference or slice the cache"
            % (q.shape[2], k.shape[2]))
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if block_q is None or block_k is None:
        # autotune needs CONCRETE arrays (it executes candidates); under
        # jit tracing fall back to the table — tune eagerly once with
        # the training shapes, then the cached winner applies
        concrete = not isinstance(q, jax.core.Tracer)
        key = (q.shape, causal)
        if key in _TUNE_CACHE:
            dq, dk = _TUNE_CACHE[key]
        elif _getenv("MXTPU_FLASH_AUTOTUNE") == "1" \
                and concrete and jax.devices()[0].platform == "tpu":
            dq, dk = _autotune_blocks(q, k, v, causal, float(scale))
        else:
            dq, dk = _default_blocks(q.shape[2])
        block_q = dq if block_q is None else block_q
        block_k = dk if block_k is None else block_k
    return _flash(q, k, v, causal, float(scale), int(block_q), int(block_k),
                  bool(interpret))
