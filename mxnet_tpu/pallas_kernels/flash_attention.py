"""Flash attention: tiled online-softmax attention as a Pallas TPU kernel.

The reference has no attention kernel at all (2019-era; its closest analog
is the fused cuDNN RNN, src/operator/rnn-inl.h). Long-context attention is
where a modern framework's FLOPs go, so this is the flagship custom
kernel: per (batch*head, q-block) grid cell, K/V stream through VMEM in
``block_k`` tiles while the m/l/o running softmax accumulates in
registers — HBM traffic is O(S·D) instead of the O(S^2) score matrix.

Composition with the parallelism layer: ring attention
(parallel/ring_attention.py) shards the sequence over the mesh and
rotates K/V via ppermute; each hop's local block product can use this
kernel, making the two-level scheme (inter-chip ring x intra-chip flash)
match Liu et al.'s blockwise formulation.

Backward uses recompute-from-inputs through the jnp reference
implementation (standard flash practice trades the stored score matrix
for recompute; here XLA differentiates the recompute).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "attention_reference"]


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain O(S^2) attention in jnp — fallback + autodiff path.
    q,k,v: [B, H, S, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # scores + softmax in fp32 regardless of input dtype — same as the
    # Pallas kernel's accumulators, so the two paths agree under AMP bf16
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row, -jnp.inf, s)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype),
                      v).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, o_scr, *,
                block_q, block_k, causal, scale, n_kblocks):
    """One (batch*head, q-block, k-block) grid cell. The TPU grid runs
    sequentially with the k axis innermost, so VMEM scratch carries the
    m/l/o online-softmax state across k steps — only one (block_k, D)
    K/V tile is resident at a time, keeping VMEM O(block) instead of
    O(seq)."""
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        o_scr[:] = jnp.zeros_like(o_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale      # (block_q, D)
        k = k_ref[0].astype(jnp.float32)              # (block_k, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_q, block_k)
        if causal:
            row = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, -jnp.inf, s)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, corr)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = corr * l_scr[:, 0] + jnp.sum(p, axis=-1)
        o_scr[:] = corr[:, None] * o_scr[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # blocks strictly above the causal triangle contribute nothing
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (o_scr[:] / l[:, None]).astype(o_ref.dtype)


def _pallas_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, \
        "sequence lengths must be multiples of the block sizes " \
        "(pad like BucketingModule pads variable-length batches)"
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    n_kblocks = sk // block_k
    kernel = functools.partial(_fwd_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               n_kblocks=n_kblocks)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),    # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),    # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),    # unnormalized output
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d)


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    if interpret or _use_pallas():
        return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)
    return attention_reference(q, k, v, causal=causal, scale=scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret), \
        (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal,
                                               scale=scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """Tiled attention. q,k,v: [B, H, S, D]. On TPU runs the Pallas
    kernel; elsewhere the jnp reference (or the kernel under
    ``interpret=True`` for testing)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, causal, float(scale), int(block_q), int(block_k),
                  bool(interpret))
