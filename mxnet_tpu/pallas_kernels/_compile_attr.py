"""First-build compile attribution for Pallas kernels (ISSUE 8c).

Every hand-written kernel registers its first build per signature in
``profiler.record_compile`` so kernel compiles show up in the same
Compile table as the imperative dispatch cache and the fused train
step (``profiler.dumps()`` / ``metrics()['compile']``). The wall time
recorded is trace+compile+first-run when the kernel is invoked
eagerly; under an ENCLOSING jit trace it prices trace construction
only — the enclosing program's own compile probe (register.py
``_compile_probe`` / FusedTrainStep AOT) attributes the XLA compile
that actually contains the kernel, so nothing is double-counted.

Steady-state cost per kernel launch is one dict lookup; kernels are
macro ops (a whole BN/matmul/optimizer pass), so this sits far below
the per-op telemetry budgets.
"""
from __future__ import annotations

import time

import jax

from .. import profiler as _profiler
from .._debug.locktrace import named_lock

__all__ = ["attributed"]

_SEEN = set()
_LOCK = named_lock("pallas.compile_attr")


def attributed(name, key, fn):
    """Run ``fn()`` (a zero-arg closure over one pallas_call launch),
    timing and recording the FIRST call per (kernel, signature) via
    ``profiler.record_compile('pallas:<name>', ...)``. Later calls run
    ``fn`` straight through."""
    sig = (name, str(key))
    if sig in _SEEN:
        return fn()
    t0 = time.perf_counter()
    out = fn()
    try:
        out = jax.block_until_ready(out)
    except Exception:
        pass  # tracers under an enclosing jit cannot block
    dur_us = (time.perf_counter() - t0) * 1e6
    with _LOCK:
        first = sig not in _SEEN
        _SEEN.add(sig)
    if first:
        _profiler.record_compile("pallas:" + name, key=str(key),
                                 dur_us=dur_us)
    return out
