"""Standalone CachedOp / JIT surface.

ref: src/imperative/cached_op.{h,cc} — the reference compiles a recorded
graph once and re-executes it with per-shape caches (SetForwardGraph
cached_op.cc:307, StaticForward :749, DynamicForward :822). That is exactly
``jax.jit``'s model: trace once per input signature, reuse the compiled
executable. This module exposes the reference's *standalone* CachedOp API
(``mx.nd.CachedOp(sym)`` callable on NDArrays) plus a functional ``jit``
decorator with the CachedOpConfig knobs (cached_op.h:35-66) mapped to XLA:

* ``static_alloc=True``  → donate input buffers where safe (pre-planned
  memory ≙ XLA buffer assignment + donation),
* ``static_shape=True``  → assert a single input signature (no re-trace),
* ``inline_limit``       → kept for parity; XLA inlines at HLO level.
"""
from __future__ import annotations

import jax

from .ndarray import NDArray

__all__ = ["CachedOp", "jit"]


def _to_jax(x):
    return x._data if isinstance(x, NDArray) else x


class CachedOp:
    """Compiled callable over a Symbol or a python function of NDArrays
    (ref: cached_op.cc:96 ctor; exposed in python via _ctypes/ndarray.py
    CachedOp). For a Symbol, inputs are bound in ``list_inputs`` order."""

    def __init__(self, sym_or_fn, static_alloc=False, static_shape=False,
                 inline_limit=2, flags=()):
        self._static_alloc = bool(static_alloc)
        self._static_shape = bool(static_shape)
        self._signature = None
        self._flags = dict(flags)
        # cache observability (MXTCachedOpGetStats): every new input
        # signature is one trace+compile, anything else is a cache hit
        self.calls = 0
        self._seen_signatures = set()
        if callable(sym_or_fn) and not hasattr(sym_or_fn, "list_inputs"):
            self._input_names = None
            raw = sym_or_fn
        else:
            sym = sym_or_fn
            self._input_names = list(sym.list_inputs())
            raw = self._symbol_fn(sym)
        self._jitted = jax.jit(raw)

    def _symbol_fn(self, sym):
        from .executor import _GraphProgram
        prog = _GraphProgram(sym)

        def raw(*arrs):
            outs, _ = prog.run(dict(zip(self._input_names, arrs)),
                               is_train=False, key=jax.random.PRNGKey(0))
            return outs
        return raw

    @property
    def compiles(self):
        return len(self._seen_signatures)

    def __call__(self, *args):
        jargs = tuple(_to_jax(a) for a in args)
        sig = tuple((a.shape, str(a.dtype)) for a in jargs)
        self.calls += 1
        self._seen_signatures.add(sig)
        if self._static_shape:
            if self._signature is None:
                self._signature = sig
            elif sig != self._signature:
                raise ValueError(
                    "CachedOp(static_shape=True) called with a new input "
                    "signature %r != %r (ref: cached_op.cc CheckDynamicShape)"
                    % (sig, self._signature))
        out = self._jitted(*jargs)
        if isinstance(out, (list, tuple)):
            outs = [NDArray(o) for o in out]
            return outs if len(outs) != 1 else outs[0]
        return NDArray(out)


def jit(fn=None, *, static_alloc=False, static_shape=False, inline_limit=2):
    """Functional decorator form: ``@mx.jit.jit`` compiles an
    NDArray-in/NDArray-out function to one XLA program (the CachedOp seam,
    SURVEY.md §3.3)."""
    def deco(f):
        op = CachedOp(f, static_alloc=static_alloc,
                      static_shape=static_shape, inline_limit=inline_limit)
        op.__name__ = getattr(f, "__name__", "jit")
        return op
    return deco(fn) if fn is not None else deco
