"""Automatic naming scopes (ref: python/mxnet/name.py NameManager/Prefix).

Symbol nodes auto-name through ``symbol._auto_name``; these context
managers interpose on that path the way the reference's thread-local
NameManager stack interposes on C-side name generation."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_current = threading.local()


def _stack():
    if not hasattr(_current, "stack"):
        _current.stack = []
    return _current.stack


class NameManager:
    """ref: name.py:27 NameManager — assigns `hint%d` names."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        """Return `name` if given, else a fresh auto name for `hint`
        (ref: name.py get)."""
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *args):
        _stack().pop()


class Prefix(NameManager):
    """ref: name.py:74 Prefix — prepend a prefix to every auto name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


def current():
    """The active NameManager, or None (module-default counters apply)."""
    stack = _stack()
    return stack[-1] if stack else None
