"""Evaluation metrics, accumulated on-device.

Own-idiom rebuild of the reference metric zoo (ref: python/mxnet/metric.py
— EvalMetric :68, CompositeEvalMetric :309, Accuracy :393, TopKAccuracy
:462, F1 :620, MCC :721, Perplexity :833, MAE :920, MSE :969, RMSE :1018,
CrossEntropy :1067, NegativeLogLikelihood :1126, PearsonCorrelation
:1187, Loss :1230, Torch/Caffe :1262, CustomMetric :1282, np :1351).

The reference pulls every batch to the host (an `.asnumpy()` per metric
per batch) and reduces with numpy. Here a metric's per-batch statistic
is a small jitted reduction that runs wherever the predictions already
live, and the running (numerator, denominator) pair stays a lazy device
scalar: `update()` enqueues async device work and returns immediately;
the only device->host sync a metric ever forces is the `float()` inside
`get()`. A fit loop logging through a Speedometer at frequent=50 hence
syncs once per 50 batches instead of once per batch (measured:
benchmark/metric_sync.py).

Exceptions by contract: CustomMetric / metric.np wrap a user-supplied
numpy feval, so their inputs are materialized every batch; F1/MCC
validate the labels-are-binary precondition lazily at read time (an
eager check would be a per-batch sync).
"""
from __future__ import annotations

import math

import numpy

from .base import string_types
from . import ndarray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register", "get"]

_REGISTRY = {}  # mxlint: disable=MX003 (populated by @register decorators at import time, single-threaded; read-only afterwards)


def register(klass):
    """Register a metric class under its lowercased class name."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def alias(*names):
    def _add(klass):
        _REGISTRY.update({n.lower(): klass for n in names})
        return klass
    return _add


def get(name):
    return _REGISTRY[name.lower()]


def create(metric, *args, **kwargs):
    """Metric from a name, callable, EvalMetric, or list thereof
    (ref: metric.py:50)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m, *args, **kwargs))
        return out
    if isinstance(metric, string_types):
        return get(metric)(*args, **kwargs)
    raise TypeError(
        "cannot create a metric from %r (want str, callable, EvalMetric, "
        "or a list of those)" % (metric,))


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Parity helper (ref: metric.py:37): compare list lengths (or full
    shapes with shape=True), optionally wrapping bare arrays in lists."""
    got = labels.shape if shape else len(labels)
    want = preds.shape if shape else len(preds)
    if got != want:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(got, want))
    if wrap:
        labels = [labels] if isinstance(labels, ndarray.NDArray) else labels
        preds = [preds] if isinstance(preds, ndarray.NDArray) else preds
    return labels, preds


def _jax_of(x):
    """The jnp array behind an update() argument, wherever it lives —
    no copy, no host transfer."""
    import jax.numpy as jnp
    return x._data if isinstance(x, ndarray.NDArray) else jnp.asarray(x)


class _Running:
    """A lazy (numerator, denominator) pair. Either side may be a host
    number or an un-materialized device scalar; `value()` holds the one
    float() sync a metric performs.

    Seeds are Python ints so integer batch statistics (hit counts,
    element counts) chain as exact device int32 sums — float32 would
    stop counting past 2^24 (~16.7M); int32 is exact to 2.1e9 samples
    between resets, which bounds the contract explicitly."""

    __slots__ = ("num", "den")

    def __init__(self):
        self.clear()

    def clear(self):
        self.num = 0
        self.den = 0

    def add(self, num, den):
        self.num = self.num + num
        self.den = self.den + den

    def value(self):
        den = float(self.den)
        return float(self.num) / den if den else float("nan")


class EvalMetric:
    """Protocol-compatible base (ref: metric.py:68): update / reset /
    reset_local / get / get_global / get_name_value / update_dict.

    Local and global windows are `_Running` pairs; `_bump` feeds both.
    The reference's sum_metric / num_inst counters survive as
    properties, reading (and syncing) the local pair on access.
    """

    def __init__(self, name, output_names=None, label_names=None,
                 **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self._local = _Running()
        self._global = _Running()
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    # -- reference-compat counter views (each access syncs) ------------
    @property
    def sum_metric(self):
        return self._local.num if isinstance(self._local.num, float) \
            else float(self._local.num)

    @sum_metric.setter
    def sum_metric(self, v):
        self._local.num = v

    @property
    def num_inst(self):
        return self._local.den if isinstance(self._local.den, float) \
            else float(self._local.den)

    @num_inst.setter
    def num_inst(self, v):
        self._local.den = v

    @property
    def global_sum_metric(self):
        return float(self._global.num)

    @property
    def global_num_inst(self):
        return float(self._global.den)

    # ------------------------------------------------------------------
    def _bump(self, num, den):
        """Fold one batch's (numerator, denominator) into the local and
        global windows — lazily if they are device scalars."""
        self._local.add(num, den)
        self._global.add(num, den)

    def get_config(self):
        config = dict(self._kwargs)
        config.update(metric=type(self).__name__, name=self.name,
                      output_names=self.output_names,
                      label_names=self.label_names)
        return config

    def update_dict(self, label, pred):
        pred = [pred[k] for k in self.output_names if k in pred] \
            if self.output_names is not None else list(pred.values())
        label = [label[k] for k in self.label_names if k in label] \
            if self.label_names is not None else list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self._local.clear()
        self._global.clear()

    def reset_local(self):
        self._local.clear()

    def get(self):
        return (self.name, self._local.value())

    def get_global(self):
        if self._has_global_stats:
            return (self.name, self._global.value())
        return self.get()

    @staticmethod
    def _as_pairs(name, value):
        names = name if isinstance(name, list) else [name]
        values = value if isinstance(value, list) else [value]
        return list(zip(names, values))

    def get_name_value(self):
        return self._as_pairs(*self.get())

    def get_global_name_value(self):
        if self._has_global_stats:
            return self._as_pairs(*self.get_global())
        return self.get_name_value()


class _DeviceMetric(EvalMetric):
    """Base for device-accumulating metrics: subclasses implement
    `_stats(label, pred) -> (numerator, denominator)` in jnp; it is
    jitted per (shape, dtype) and executed where the batch lives, and
    the returned scalars are folded into the running pairs without
    materialization."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        import jax
        # mxlint: disable=MX005 (per-metric-instance jit of one fixed reduction: a single key per label/pred shape, bounded by the eval loop's shapes)
        self._reduce = jax.jit(self._stats)

    def _stats(self, label, pred):
        raise NotImplementedError

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._bump(*self._reduce(_jax_of(label), _jax_of(pred)))


@register
@alias("acc")
class Accuracy(_DeviceMetric):
    """Fraction of argmax predictions matching the label
    (ref: metric.py:393)."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        self.axis = axis
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def _stats(self, label, pred):
        import jax.numpy as jnp
        if pred.shape != label.shape:  # class scores -> class index
            pred = jnp.argmax(pred, axis=self.axis)
        hits = jnp.sum(pred.ravel().astype(jnp.int32)
                       == label.ravel().astype(jnp.int32))
        return hits, label.size


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(_DeviceMetric):
    """Label-in-top-k rate over 2-D score matrices
    (ref: metric.py:462 — which walks the k argsort columns; lax.top_k
    counts the same membership in one fused kernel)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        if top_k <= 1:
            raise ValueError("use Accuracy for top_k <= 1")
        self.top_k = top_k
        super().__init__("%s_%d" % (name, top_k), top_k=top_k,
                         output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def _stats(self, label, pred):
        import jax
        import jax.numpy as jnp
        if pred.ndim > 2:
            raise ValueError("predictions must be 1-D or 2-D, got %d-D"
                             % pred.ndim)
        # (N, 1) column labels must flatten before broadcasting against
        # the k argsort columns (ref uses label_np.flat): without the
        # ravel, label[:, None] is (N, 1, 1) and the == broadcasts to
        # (N, N, k), counting cross-row matches — accuracy above 1.0
        label = label.ravel()
        if pred.ndim == 1:
            hits = jnp.sum(pred.astype(jnp.int32)
                           == label.astype(jnp.int32))
        else:
            k = min(self.top_k, pred.shape[1])
            _, top = jax.lax.top_k(pred.astype(jnp.float32), k)
            hits = jnp.sum(top == label.astype(top.dtype)[:, None])
        return hits, pred.shape[0]


class _ConfusionCounts:
    """Lazy device confusion matrix for the binary F-family
    (ref helper: metric.py:547 _BinaryClassificationMetrics). Each
    update adds four un-materialized scalars; `snapshot()` returns the
    lazy (tp, fp, fn, tn) tuple, and reads happen only inside the
    owning metric's get()."""

    def __init__(self):
        import jax
        # mxlint: disable=MX005 (per-instance jit of the fixed 4-cell confusion tally; one key per batch shape)
        self._tally = jax.jit(self._batch_tally)
        self.reset_stats()

    @staticmethod
    def _batch_tally(label, pred):
        import jax.numpy as jnp
        yes = jnp.argmax(pred, axis=1) == 1
        truth = label.ravel().astype(jnp.int32) == 1
        tp = jnp.sum(yes & truth)
        fp = jnp.sum(yes & ~truth)
        fn = jnp.sum(~yes & truth)
        tn = jnp.sum(~yes & ~truth)
        # labels outside {0, 1} make the four cells no longer partition
        # the batch; carried along for the lazy binary check
        bad = jnp.sum(label.ravel().astype(jnp.int32) > 1)
        return tp, fp, fn, tn, bad

    def update_binary_stats(self, label, pred):
        tp, fp, fn, tn, bad = self._tally(_jax_of(label), _jax_of(pred))
        self.true_positives = self.true_positives + tp
        self.false_positives = self.false_positives + fp
        self.false_negatives = self.false_negatives + fn
        self.true_negatives = self.true_negatives + tn
        self._bad = self._bad + bad

    def snapshot(self):
        return (self.true_positives, self.false_positives,
                self.false_negatives, self.true_negatives, self._bad)

    def reset_stats(self):
        self.true_positives = 0
        self.false_positives = 0
        self.false_negatives = 0
        self.true_negatives = 0
        self._bad = 0


def _fscore(tp, fp, fn, tn, bad):
    if bad:
        raise ValueError("F1 supports binary labels only; saw a label "
                         "> 1 (checked lazily at read time)")
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    if precision + recall == 0.0:
        return 0.0
    return 2 * precision * recall / (precision + recall)


def _matthews(tp, fp, fn, tn, bad):
    if bad:
        raise ValueError("MCC supports binary labels only; saw a label "
                         "> 1 (checked lazily at read time)")
    if not (tp + fp + fn + tn):
        return 0.0
    denom = 1.0
    for t in (tp + fp, tp + fn, tn + fp, tn + fn):
        denom *= t or 1.0
    return (tp * tn - fp * fn) / math.sqrt(denom)


class _FFamily(EvalMetric):
    """Shared frame of F1 and MCC: a device confusion matrix, read
    through a score function at get(). average="macro" keeps one lazy
    snapshot PER BATCH and averages their scores at read time — same
    semantics as the reference's per-update score-and-reset, but with
    zero per-batch syncs; "micro" pools the counts."""

    _score = None  # staticmethod(_fscore | _matthews)

    def __init__(self, name, output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._counts = _ConfusionCounts()
        self._snapshots = []
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            if label.shape[0] != pred.shape[0]:
                raise ValueError("label rows %d != pred rows %d"
                                 % (label.shape[0], pred.shape[0]))
            self._counts.update_binary_stats(label, pred)
        if self.average == "macro":
            self._snapshots.append(self._counts.snapshot())
            self._counts.reset_stats()

    def get(self):
        import jax
        score = type(self)._score
        if self.average == "macro":
            if not self._snapshots:
                return (self.name, float("nan"))
            # ONE batched transfer for every pending snapshot, then
            # cache the host tuples so re-reads are free and the device
            # buffers are released
            self._snapshots = [
                tuple(float(c) for c in s)
                for s in jax.device_get(self._snapshots)]
            vals = [score(*s) for s in self._snapshots]
            return (self.name, sum(vals) / len(vals))
        cells = [float(c)
                 for c in jax.device_get(self._counts.snapshot())]
        if not sum(cells[:4]):
            return (self.name, float("nan"))
        return (self.name, score(*cells))

    get_global = get

    def reset(self):
        self._snapshots = []
        self._counts.reset_stats()
        super().reset()

    reset_local = reset


@register
class F1(_FFamily):
    """Binary F1 (ref: metric.py:620)."""

    _score = staticmethod(_fscore)

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average)


@register
class MCC(_FFamily):
    """Matthews correlation coefficient (ref: metric.py:721)."""

    _score = staticmethod(_matthews)

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average)


@register
class Perplexity(_DeviceMetric):
    """exp of the mean negative log picked-probability, optionally
    skipping ignore_label positions (ref: metric.py:833)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        self.ignore_label = ignore_label
        self.axis = axis
        super().__init__(name, ignore_label=ignore_label,
                         output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def _stats(self, label, pred):
        import jax.numpy as jnp
        classes = pred.shape[-1]
        assert label.size * classes == pred.size, \
            "label/pred shape mismatch"
        idx = label.ravel().astype(jnp.int32)
        p = jnp.take_along_axis(pred.reshape(-1, classes), idx[:, None],
                                axis=1)[:, 0]
        n = idx.size
        if self.ignore_label is not None:
            keep = idx != self.ignore_label
            p = jnp.where(keep, p, 1.0)
            n = jnp.sum(keep)
        return -jnp.sum(jnp.log(jnp.maximum(p, 1e-10))), n

    def get(self):
        v = self._local.value()
        return (self.name, math.exp(v) if v == v else v)

    def get_global(self):
        v = self._global.value()
        return (self.name, math.exp(v) if v == v else v)


class _PerBatchMean(_DeviceMetric):
    """Regression-style metrics: one scalar per batch, averaged over
    batches (den advances by 1 per update, like the reference)."""

    _default_name = None

    def __init__(self, name=None, output_names=None, label_names=None):
        super().__init__(name or self._default_name,
                         output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def _stats(self, label, pred):
        return self._batch_value(label, pred), 1


@register
@alias("mae")
class MAE(_PerBatchMean):
    """Mean absolute error (ref: metric.py:920)."""

    _default_name = "mae"

    def _batch_value(self, label, pred):
        import jax.numpy as jnp
        return jnp.mean(jnp.abs(label - pred))


@register
@alias("mse")
class MSE(_PerBatchMean):
    """Mean squared error (ref: metric.py:969)."""

    _default_name = "mse"

    def _batch_value(self, label, pred):
        import jax.numpy as jnp
        return jnp.mean(jnp.square(label - pred))


@register
@alias("rmse")
class RMSE(_PerBatchMean):
    """Root mean squared error, per batch (ref: metric.py:1018 — note
    the reference averages per-batch roots, not the root of the pooled
    mean; kept)."""

    _default_name = "rmse"

    def _batch_value(self, label, pred):
        import jax.numpy as jnp
        return jnp.sqrt(jnp.mean(jnp.square(label - pred)))


@register
@alias("pearsonr")
class PearsonCorrelation(_PerBatchMean):
    """Per-batch Pearson r (ref: metric.py:1187), as centered
    cross-moments on the device instead of host corrcoef."""

    _default_name = "pearsonr"

    def _batch_value(self, label, pred):
        import jax.numpy as jnp
        x = pred.ravel().astype(jnp.float32)
        y = label.ravel().astype(jnp.float32)
        xc = x - jnp.mean(x)
        yc = y - jnp.mean(y)
        return jnp.sum(xc * yc) / jnp.sqrt(
            jnp.sum(jnp.square(xc)) * jnp.sum(jnp.square(yc)))

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, False, True)
            self._bump(*self._reduce(_jax_of(label), _jax_of(pred)))


class _PickedLogProb(_DeviceMetric):
    """-sum(log p[label]) over a [N, C] probability matrix, averaged
    over the N rows — the shape CrossEntropy and NLL share."""

    def __init__(self, eps=1e-12, name=None, output_names=None,
                 label_names=None):
        self.eps = eps
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def _stats(self, label, pred):
        import jax.numpy as jnp
        idx = label.ravel().astype(jnp.int32)
        assert idx.size == pred.shape[0], (idx.size, pred.shape)
        p = jnp.take_along_axis(pred, idx[:, None], axis=1)[:, 0]
        return -jnp.sum(jnp.log(p + self.eps)), idx.size


@register
@alias("ce")
class CrossEntropy(_PickedLogProb):
    """ref: metric.py:1067."""

    def __init__(self, eps=1e-12, name="cross-entropy",
                 output_names=None, label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
@alias("nll_loss")
class NegativeLogLikelihood(_PickedLogProb):
    """ref: metric.py:1126."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class Loss(EvalMetric):
    """Running mean of whatever the outputs are — the print-the-loss
    metric (ref: metric.py:1230)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, _, preds):
        import jax.numpy as jnp
        if isinstance(preds, ndarray.NDArray):
            preds = [preds]
        for pred in preds:
            arr = _jax_of(pred)
            self._bump(jnp.sum(arr), arr.size)


@register
class Torch(Loss):
    """Alias frame for torch criterions (ref: metric.py:1262)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """ref: metric.py:1273."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """User-supplied numpy feval (ref: metric.py:1282). By contract the
    feval sees numpy arrays, so this is the one metric that materializes
    its inputs every update."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:  # lambdas etc.
                name = "custom(%s)" % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            out = self._feval(label.asnumpy(), pred.asnumpy())
            self._bump(*(out if isinstance(out, tuple) else (out, 1)))

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a bare numpy feval(label, pred) as a metric
    (ref: metric.py:1351)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


@register
@alias("composite")
class CompositeEvalMetric(EvalMetric):
    """Fans update/reset/get out over child metrics (ref: metric.py:309)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.metrics = [create(m) for m in metrics or []]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            # the reference RETURNS this exception (metric.py:344) — an
            # upstream wart, fixed here by actually raising
            raise ValueError("Metric index {} is out of range 0 and {}"
                             .format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {k: v for k, v in labels.items()
                      if k in self.label_names}
        if self.output_names is not None:
            preds = {k: v for k, v in preds.items()
                     if k in self.output_names}
        for m in self.metrics:
            m.update_dict(labels, preds)

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", ()):
            m.reset()

    def reset_local(self):
        for m in getattr(self, "metrics", ()):
            m.reset_local()

    def _gather(self, one):
        names, values = [], []
        for m in self.metrics:
            name, value = one(m)
            names += name if isinstance(name, list) else [name]
            values += value if isinstance(value, list) else [value]
        return (names, values)

    def get(self):
        return self._gather(lambda m: m.get())

    def get_global(self):
        return self._gather(lambda m: m.get_global())

    def get_config(self):
        config = super().get_config()
        config.update(metrics=[m.get_config() for m in self.metrics])
        return config
