"""KVStore server role shim (ref: python/mxnet/kvstore_server.py).

The reference's `dist_*` modes run dedicated server processes: a worker
pickles its optimizer, ships it over the ps-lite command channel, and the
server applies updates (`_controller` dispatching kCommandController).
On TPU there are no server processes — aggregation is XLA collectives and
"server-side" optimizer state is sharded optimizer state under pjit
(SURVEY.md §5) — so `_init_kvstore_server_module` is a no-op that returns
immediately on every rank instead of trapping server roles in a serve
loop. `KVStoreServer` keeps the API for launch scripts that construct it.
"""
from __future__ import annotations

import pickle
import threading
import time as _time

__all__ = ["KVStoreServer", "SnapshotTable",
           "_init_kvstore_server_module"]


class SnapshotTable:
    """Server-side peer-snapshot store (ISSUE 19c): the newest
    in-memory training-state blob each live rank published, so a rank
    restarting after a failure can pull a peer's state over the wire
    instead of walking back to the checkpoint filesystem.

    Blobs are OPAQUE here — HMAC tag + pickle produced and verified by
    ``parallel.elastic`` on the worker side; the server stores and
    serves bytes, never unpickles (the v1 data-plane no-pickle
    contract). One slot per rank: a publish replaces that rank's
    previous snapshot, so the table is bounded by world size, not by
    run length. ``get_newest`` picks the highest-step snapshot among
    ranks that are both not the requester and alive by the heartbeat
    table the server already keeps — a dead rank's stale snapshot must
    never win over a live peer's fresher one. Equal-step candidates
    tie-break on the LOWEST rank (ISSUE 20 satellite) — the winner is a
    pure function of the table's contents, never of dict iteration
    order, so every requester recovering from the same table restores
    from the same peer.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}  # rank -> (step, blob, monotonic publish ts)

    def put(self, rank, step, blob):
        with self._lock:
            self._slots[int(rank)] = (int(step), bytes(blob),
                                      _time.monotonic())

    def get_newest(self, exclude_rank, heartbeats, stale_timeout):
        """Best ``(rank, step, blob)`` from a live peer, or ``None``.

        ``heartbeats`` is the server's {rank: last monotonic heartbeat}
        table; a publisher whose heartbeat is older than
        ``stale_timeout`` seconds (or absent) is skipped — its snapshot
        may predate the very failure the requester is recovering from.
        ``stale_timeout <= 0`` disables the liveness filter (tests, or
        single-controller setups that prune slots themselves).
        """
        now = _time.monotonic()
        best = None
        with self._lock:
            for rank, (step, blob, _ts) in self._slots.items():
                if rank == int(exclude_rank):
                    continue
                if stale_timeout > 0:
                    hb = heartbeats.get(rank)
                    if hb is None or (now - hb) > stale_timeout:
                        continue
                if best is None or step > best[1] \
                        or (step == best[1] and rank < best[0]):
                    best = (rank, step, blob)
        return best

    def items(self):
        """Point-in-time ``[(rank, step, blob)]`` in rank order (the
        journal-compaction walk, tests)."""
        with self._lock:
            return [(r, s, b) for r, (s, b, _ts)
                    in sorted(self._slots.items())]

    def drop(self, rank):
        with self._lock:
            self._slots.pop(int(rank), None)

    def __len__(self):
        with self._lock:
            return len(self._slots)


class KVStoreServer:
    """ref: kvstore_server.py:28 KVStoreServer."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging = False

    def _controller(self):
        """ref: kvstore_server.py _controller — decode a pickled optimizer
        sent by rank 0 and install it (the command channel collapses to a
        direct call in-process)."""
        def server_controller(cmd_id, cmd_body):
            if cmd_id == 0:
                optimizer = pickle.loads(cmd_body if isinstance(
                    cmd_body, bytes) else cmd_body.encode("latin1"))
                self.kvstore.set_optimizer(optimizer)
            return None
        return server_controller

    def run(self):
        """ref: kvstore_server.py run — the reference blocks in the
        ps-lite serve loop; with collectives there is nothing to serve."""
        return None


def _init_kvstore_server_module():
    """ref: kvstore_server.py:85 — the reference traps DMLC_ROLE=server
    processes into the ps-lite serve loop here. All ranks are workers in
    this framework (aggregation is collective, "server" state is sharded
    optimizer state), so there is deliberately nothing to do."""


_init_kvstore_server_module()
