"""KVStore server role shim (ref: python/mxnet/kvstore_server.py).

The reference's `dist_*` modes run dedicated server processes: a worker
pickles its optimizer, ships it over the ps-lite command channel, and the
server applies updates (`_controller` dispatching kCommandController).
On TPU there are no server processes — aggregation is XLA collectives and
"server-side" optimizer state is sharded optimizer state under pjit
(SURVEY.md §5) — so `_init_kvstore_server_module` is a no-op that returns
immediately on every rank instead of trapping server roles in a serve
loop. `KVStoreServer` keeps the API for launch scripts that construct it.
"""
from __future__ import annotations

import pickle

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """ref: kvstore_server.py:28 KVStoreServer."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging = False

    def _controller(self):
        """ref: kvstore_server.py _controller — decode a pickled optimizer
        sent by rank 0 and install it (the command channel collapses to a
        direct call in-process)."""
        def server_controller(cmd_id, cmd_body):
            if cmd_id == 0:
                optimizer = pickle.loads(cmd_body if isinstance(
                    cmd_body, bytes) else cmd_body.encode("latin1"))
                self.kvstore.set_optimizer(optimizer)
            return None
        return server_controller

    def run(self):
        """ref: kvstore_server.py run — the reference blocks in the
        ps-lite serve loop; with collectives there is nothing to serve."""
        return None


def _init_kvstore_server_module():
    """ref: kvstore_server.py:85 — the reference traps DMLC_ROLE=server
    processes into the ps-lite serve loop here. All ranks are workers in
    this framework (aggregation is collective, "server" state is sharded
    optimizer state), so there is deliberately nothing to do."""


_init_kvstore_server_module()
