"""``mx.sym.image`` namespace (ref: python/mxnet/symbol/image.py —
generated from the `_image_*` registry entries like nd.image)."""
from __future__ import annotations

from ..ops import registry as _registry
from .register import make_symbol_op_func

__all__ = []


def _populate_image():
    g = globals()
    for name in _registry.list_ops():
        if name.startswith("_image_"):
            short = name[len("_image_"):]
            if short not in g:
                g[short] = make_symbol_op_func(_registry.get_op(name),
                                               short)
                __all__.append(short)


_populate_image()
