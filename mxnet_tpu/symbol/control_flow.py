"""Symbol-level control flow: subgraph capture + XLA-native lowering.

The reference implements `sym.contrib.foreach/while_loop/cond` as stateful
C++ ops holding nnvm subgraphs (ref: src/operator/control_flow.cc:1089
_foreach, :1150 _while_loop, :1211 _cond; python capture in
python/mxnet/symbol/contrib.py:212,375,598). Here a control-flow node
stores its subgraph(s) as serialized graph JSON in node attrs, and the
executor lowers the whole node into the enclosing XLA program via
`lax.scan` / `lax.while_loop` / `lax.cond` — compiler-friendly loops
instead of the reference's per-step engine pushes, which is exactly the
control-flow story the TPU design calls for (no data-dependent Python
control flow inside jit).

Capture works by creation order: every `_Node` carries a monotonically
increasing `uid`. Anything the body references that was created BEFORE the
capture started (outer op results) — and every free variable — is "cut"
into an explicit input of the control-flow node, mirroring the reference's
closure-capture of outer symbols.
"""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
from jax import lax

from .symbol import Symbol, _Node, _node_uid

__all__ = ["CONTROL_FLOW_OPS", "capture_subgraph", "lower"]

CONTROL_FLOW_OPS = ("_foreach", "_while_loop", "_cond")


def capture_subgraph(heads, placeholders, marker):
    """Serialize the graph reachable from `heads` into standalone JSON.

    heads        : list[(node, out_index)] subgraph outputs
    placeholders : {id(node): varname} — loop placeholders, kept as subgraph
                   input variables under the given name
    marker       : uid watermark; nodes with uid < marker are outer values

    Free variables and outer op results become fresh input variables of the
    subgraph ("cuts"). Returns (json_str, input_varnames, cut_entries) where
    cut_entries is the ordered list of outer (node, out_index) pairs feeding
    the cut variables, and input_varnames lists every subgraph input
    variable name in [placeholder..., cut...] order.
    """
    memo = {}       # id(inner node) -> copied node
    cut_memo = {}   # (id(node), oi) -> copied var node
    cuts = []       # [(node, oi)] outer values, in first-use order
    cut_names = []

    def is_boundary(node):
        return (id(node) not in placeholders
                and (node.is_variable() or node.uid < marker))

    def cut_var(src, oi):
        k = (id(src), oi)
        if k in cut_memo:
            return cut_memo[k]
        if src.is_variable():
            name = src.name               # keep bindable parameter names
        else:
            name = "_cut_%s_out%d" % (src.name, oi)
        nn = _Node(None, name, {})
        cut_memo[k] = nn
        cuts.append((src, oi))
        cut_names.append(name)
        return nn

    def copy(node):
        if id(node) in memo:
            return memo[id(node)]
        if id(node) in placeholders:
            nn = _Node(None, placeholders[id(node)], {})
        else:
            nn = _Node(node.op, node.name, dict(node.attrs), (),
                       node.num_outputs)
            for src, oi in node.inputs:
                if is_boundary(src):
                    nn.inputs.append((cut_var(src, oi), 0))
                else:
                    nn.inputs.append((copy(src), oi))
        memo[id(node)] = nn
        return nn

    new_heads = []
    for node, oi in heads:
        if is_boundary(node):
            new_heads.append((cut_var(node, oi), 0))
        else:
            new_heads.append((copy(node), oi))
    sub = Symbol(new_heads)
    input_names = list(placeholders.values()) + cut_names
    return sub.tojson(), input_names, cuts


def _programs(node):
    """Parse (and cache) the node's subgraph JSON into graph programs."""
    if node._cf_cache is None:
        from .symbol import load_json
        from ..executor import _GraphProgram
        node._cf_cache = [_GraphProgram(load_json(js))
                          for js in node.attrs["__subgraph__"]]
    return node._cf_cache


def _bind(mapping, node_ins, carry, slices):
    """Resolve a subgraph's {varname: value} dict from its input mapping.

    mapping entries are [varname, kind, idx]:
      kind "slice" — per-step slice idx of the scanned sequences
      kind "carry" — loop-carried value idx
      kind "input" — node input idx (closure / initial value)
    """
    values = {}
    for name, kind, idx in mapping:
        if kind == "slice":
            values[name] = slices[idx]
        elif kind == "carry":
            values[name] = carry[idx]
        else:
            values[name] = node_ins[idx]
    return values


def lower(node, ins, is_train, key):
    """Lower one control-flow node to jax. ins: node input values in node
    input order. Returns (outputs list, aux_updates dict) — aux updates
    (BatchNorm moving stats inside the subgraph) are threaded through the
    loop carry and keyed by the OUTER variable name (subgraph cutting
    preserves variable names), so the executor merges them like any other
    aux write."""
    if node.op == "_foreach":
        return _lower_foreach(node, ins, is_train, key)
    if node.op == "_while_loop":
        return _lower_while(node, ins, is_train, key)
    if node.op == "_cond":
        return _lower_cond(node, ins, is_train, key)
    raise ValueError(node.op)


def _probe_aux_keys(prog, values, is_train):
    """Statically determine which aux vars the subgraph updates."""
    if not is_train:
        return []

    def f(vals, k):
        return prog.run(vals, True, k)[1]

    try:
        aux_shapes = jax.eval_shape(f, values, jax.random.PRNGKey(0))
    except Exception:
        return []
    return sorted(aux_shapes)


def _input_value(mappings, ins, name):
    """The outer value feeding subgraph variable `name` (input-kind)."""
    for mapping in mappings:
        for vn, kind, idx in mapping:
            if vn == name and kind == "input":
                return ins[idx]
    return None


def _lower_foreach(node, ins, is_train, key):
    a = node.attrs
    nd_, ns_ = int(a["__num_data__"]), int(a["__num_states__"])
    nod = int(a["__num_out_data__"])
    (mapping,) = a["__subg_inputs__"]
    (prog,) = _programs(node)
    data = tuple(ins[:nd_])
    states0 = tuple(ins[nd_:nd_ + ns_])
    length = data[0].shape[0]

    probe_vals = _bind(mapping, ins, states0, tuple(d[0] for d in data))
    aux_keys = _probe_aux_keys(prog, probe_vals, is_train)
    aux0 = tuple(_input_value([mapping], ins, k) for k in aux_keys)

    def body(carry, xs):
        states, aux = carry
        slices, t = xs
        values = _bind(mapping, ins, states, slices)
        values.update(zip(aux_keys, aux))   # current moving stats
        outs, aux_up = prog.run(values, is_train,
                                jax.random.fold_in(key, t))
        new_aux = tuple(aux_up.get(k, v) for k, v in zip(aux_keys, aux))
        return (tuple(outs[nod:]), new_aux), tuple(outs[:nod])

    (final, aux_f), stacked = lax.scan(
        body, (states0, aux0),
        (data, jnp.arange(length, dtype=jnp.int32)))
    return (list(stacked) + list(final),
            dict(zip(aux_keys, aux_f)))


def _lower_while(node, ins, is_train, key):
    a = node.attrs
    nvars = int(a["__num_vars__"])
    nod = int(a["__num_out_data__"])
    max_iter = int(a["max_iterations"])
    map_cond, map_body = a["__subg_inputs__"]
    prog_cond, prog_body = _programs(node)
    loop0 = tuple(ins[:nvars])

    probe_vals = _bind(map_body, ins, loop0, ())
    aux_keys = _probe_aux_keys(prog_body, probe_vals, is_train)
    aux0 = tuple(_input_value([map_body], ins, k) for k in aux_keys)

    def run_body(vars_, aux, t):
        values = _bind(map_body, ins, vars_, ())
        values.update(zip(aux_keys, aux))
        outs, aux_up = prog_body.run(values, is_train,
                                     jax.random.fold_in(key, t))
        new_aux = tuple(aux_up.get(k, v) for k, v in zip(aux_keys, aux))
        return tuple(outs), new_aux

    out_shapes = jax.eval_shape(run_body, loop0, aux0, jnp.int32(0))[0][:nod]
    bufs0 = tuple(jnp.zeros((max_iter,) + s.shape, s.dtype)
                  for s in out_shapes)

    def cond_fn(st):
        i, vars_, _, _ = st
        values = _bind(map_cond, ins, vars_, ())
        outs, _ = prog_cond.run(values, is_train, key)
        p = jnp.reshape(outs[0].astype(bool), ())
        return jnp.logical_and(i < max_iter, p)

    def body_fn(st):
        i, vars_, bufs, aux = st
        outs, new_aux = run_body(vars_, aux, i)
        step_outs, new_vars = outs[:nod], outs[nod:]
        bufs = tuple(lax.dynamic_update_index_in_dim(
            b, o.astype(b.dtype), i, 0) for b, o in zip(bufs, step_outs))
        return i + 1, tuple(new_vars), bufs, new_aux

    _, vars_, bufs, aux_f = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), loop0, bufs0, aux0))
    return list(bufs) + list(vars_), dict(zip(aux_keys, aux_f))


def _lower_cond(node, ins, is_train, key):
    a = node.attrs
    map_pred, map_then, map_else = a["__subg_inputs__"]
    prog_pred, prog_then, prog_else = _programs(node)

    pred_outs, pred_aux = prog_pred.run(_bind(map_pred, ins, (), ()),
                                        is_train, key)
    pred = jnp.reshape(pred_outs[0].astype(bool), ())

    aux_keys = sorted(set(
        _probe_aux_keys(prog_then, _bind(map_then, ins, (), ()), is_train)
        + _probe_aux_keys(prog_else, _bind(map_else, ins, (), ()),
                          is_train)))
    mappings = [map_pred, map_then, map_else]

    def mk(prog, mapping, salt):
        def branch(_):
            values = _bind(mapping, ins, (), ())
            outs, aux_up = prog.run(values, is_train,
                                    jax.random.fold_in(key, salt))
            # untaken-branch aux stays at the incoming value
            aux_vals = tuple(
                aux_up.get(k, _input_value(mappings, ins, k))
                for k in aux_keys)
            return tuple(outs) + aux_vals
        return branch

    res = lax.cond(pred, mk(prog_then, map_then, 1),
                   mk(prog_else, map_else, 2), jnp.int32(0))
    n_out = len(res) - len(aux_keys)
    aux = dict(pred_aux)
    aux.update(zip(aux_keys, res[n_out:]))
    return list(res[:n_out]), aux


def next_marker():
    """uid watermark for capture: nodes created after this call have
    uid >= the returned value."""
    return next(_node_uid)
