"""Symbol-level control flow: subgraph capture + XLA-native lowering.

The reference implements `sym.contrib.foreach/while_loop/cond` as stateful
C++ ops holding nnvm subgraphs (ref: src/operator/control_flow.cc:1089
_foreach, :1150 _while_loop, :1211 _cond; python capture in
python/mxnet/symbol/contrib.py:212,375,598). Here a control-flow node
stores its subgraph(s) as serialized graph JSON in node attrs, and the
executor lowers the whole node into the enclosing XLA program via
`lax.scan` / `lax.while_loop` / `lax.cond` — compiler-friendly loops
instead of the reference's per-step engine pushes, which is exactly the
control-flow story the TPU design calls for (no data-dependent Python
control flow inside jit).

Capture works by creation order: every `_Node` carries a monotonically
increasing `uid`. Anything the body references that was created BEFORE the
capture started (outer op results) — and every free variable — is "cut"
into an explicit input of the control-flow node, mirroring the reference's
closure-capture of outer symbols.
"""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
from jax import lax

from .symbol import Symbol, _Node, _node_uid

__all__ = ["CONTROL_FLOW_OPS", "capture_subgraph", "lower"]

CONTROL_FLOW_OPS = ("_foreach", "_while_loop", "_cond")


def capture_subgraph(heads, placeholders, marker):
    """Serialize the graph reachable from `heads` into standalone JSON.

    heads        : list[(node, out_index)] subgraph outputs
    placeholders : {id(node): varname} — loop placeholders, kept as subgraph
                   input variables under the given name
    marker       : uid watermark; nodes with uid < marker are outer values

    Free variables and outer op results become fresh input variables of the
    subgraph ("cuts"). Returns (json_str, input_varnames, cut_entries) where
    cut_entries is the ordered list of outer (node, out_index) pairs feeding
    the cut variables, and input_varnames lists every subgraph input
    variable name in [placeholder..., cut...] order.
    """
    memo = {}       # id(inner node) -> copied node
    cut_memo = {}   # (id(node), oi) -> copied var node
    cuts = []       # [(node, oi)] outer values, in first-use order
    cut_names = []

    def is_boundary(node):
        return (id(node) not in placeholders
                and (node.is_variable() or node.uid < marker))

    def cut_var(src, oi):
        k = (id(src), oi)
        if k in cut_memo:
            return cut_memo[k]
        if src.is_variable():
            name = src.name               # keep bindable parameter names
        else:
            name = "_cut_%s_out%d" % (src.name, oi)
        nn = _Node(None, name, {})
        cut_memo[k] = nn
        cuts.append((src, oi))
        cut_names.append(name)
        return nn

    def copy(node):
        if id(node) in memo:
            return memo[id(node)]
        if id(node) in placeholders:
            nn = _Node(None, placeholders[id(node)], {})
        else:
            nn = _Node(node.op, node.name, dict(node.attrs), (),
                       node.num_outputs)
            for src, oi in node.inputs:
                if is_boundary(src):
                    nn.inputs.append((cut_var(src, oi), 0))
                else:
                    nn.inputs.append((copy(src), oi))
        memo[id(node)] = nn
        return nn

    new_heads = []
    for node, oi in heads:
        if is_boundary(node):
            new_heads.append((cut_var(node, oi), 0))
        else:
            new_heads.append((copy(node), oi))
    sub = Symbol(new_heads)
    input_names = list(placeholders.values()) + cut_names
    return sub.tojson(), input_names, cuts


def _programs(node):
    """Parse (and cache) the node's subgraph JSON into graph programs."""
    if node._cf_cache is None:
        from .symbol import load_json
        from ..executor import _GraphProgram
        node._cf_cache = [_GraphProgram(load_json(js))
                          for js in node.attrs["__subgraph__"]]
    return node._cf_cache


def _bind(mapping, node_ins, carry, slices):
    """Resolve a subgraph's {varname: value} dict from its input mapping.

    mapping entries are [varname, kind, idx]:
      kind "slice" — per-step slice idx of the scanned sequences
      kind "carry" — loop-carried value idx
      kind "input" — node input idx (closure / initial value)
    """
    values = {}
    for name, kind, idx in mapping:
        if kind == "slice":
            values[name] = slices[idx]
        elif kind == "carry":
            values[name] = carry[idx]
        else:
            values[name] = node_ins[idx]
    return values


def lower(node, ins, is_train, key):
    """Lower one control-flow node to jax. ins: node input values in node
    input order. Returns the node's output values as a list."""
    if node.op == "_foreach":
        return _lower_foreach(node, ins, is_train, key)
    if node.op == "_while_loop":
        return _lower_while(node, ins, is_train, key)
    if node.op == "_cond":
        return _lower_cond(node, ins, is_train, key)
    raise ValueError(node.op)


def _lower_foreach(node, ins, is_train, key):
    a = node.attrs
    nd_, ns_ = int(a["__num_data__"]), int(a["__num_states__"])
    nod = int(a["__num_out_data__"])
    (mapping,) = a["__subg_inputs__"]
    (prog,) = _programs(node)
    data = tuple(ins[:nd_])
    states0 = tuple(ins[nd_:nd_ + ns_])
    length = data[0].shape[0]

    def body(carry, xs):
        slices, t = xs
        values = _bind(mapping, ins, carry, slices)
        outs, _ = prog.run(values, is_train, jax.random.fold_in(key, t))
        return tuple(outs[nod:]), tuple(outs[:nod])

    final, stacked = lax.scan(body, states0,
                              (data, jnp.arange(length, dtype=jnp.int32)))
    return list(stacked) + list(final)


def _lower_while(node, ins, is_train, key):
    a = node.attrs
    nvars = int(a["__num_vars__"])
    nod = int(a["__num_out_data__"])
    max_iter = int(a["max_iterations"])
    map_cond, map_body = a["__subg_inputs__"]
    prog_cond, prog_body = _programs(node)
    loop0 = tuple(ins[:nvars])

    def run_body(vars_, t):
        values = _bind(map_body, ins, vars_, ())
        outs, _ = prog_body.run(values, is_train, jax.random.fold_in(key, t))
        return tuple(outs)

    out_shapes = jax.eval_shape(run_body, loop0, jnp.int32(0))[:nod]
    bufs0 = tuple(jnp.zeros((max_iter,) + s.shape, s.dtype)
                  for s in out_shapes)

    def cond_fn(st):
        i, vars_, _ = st
        values = _bind(map_cond, ins, vars_, ())
        outs, _ = prog_cond.run(values, is_train, key)
        p = jnp.reshape(outs[0].astype(bool), ())
        return jnp.logical_and(i < max_iter, p)

    def body_fn(st):
        i, vars_, bufs = st
        outs = run_body(vars_, i)
        step_outs, new_vars = outs[:nod], outs[nod:]
        bufs = tuple(lax.dynamic_update_index_in_dim(
            b, o.astype(b.dtype), i, 0) for b, o in zip(bufs, step_outs))
        return i + 1, tuple(new_vars), bufs

    _, vars_, bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), loop0, bufs0))
    return list(bufs) + list(vars_)


def _lower_cond(node, ins, is_train, key):
    a = node.attrs
    map_pred, map_then, map_else = a["__subg_inputs__"]
    prog_pred, prog_then, prog_else = _programs(node)

    pred_outs, _ = prog_pred.run(_bind(map_pred, ins, (), ()), is_train, key)
    pred = jnp.reshape(pred_outs[0].astype(bool), ())

    def mk(prog, mapping, salt):
        def branch(_):
            values = _bind(mapping, ins, (), ())
            outs, _ = prog.run(values, is_train,
                               jax.random.fold_in(key, salt))
            return tuple(outs)
        return branch

    outs = lax.cond(pred, mk(prog_then, map_then, 1),
                    mk(prog_else, map_else, 2), jnp.int32(0))
    return list(outs)


def next_marker():
    """uid watermark for capture: nodes created after this call have
    uid >= the returned value."""
    return next(_node_uid)
