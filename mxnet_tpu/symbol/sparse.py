"""``mx.sym.sparse`` namespace (ref: python/mxnet/symbol/sparse.py).

Sparse STORAGE is an NDArray-level concept here (XLA tensors are dense;
see ndarray/sparse.py) — the symbolic namespace exposes the graph ops:
``cast_storage`` is identity, ``retain`` is the dense row-masking
emulation, ``dot`` is the shared dot op. Imperative-only constructors
(csr_matrix/row_sparse_array) stay on the nd side."""
from __future__ import annotations

from ..ops import registry as _registry
from .register import make_symbol_op_func
from .symbol import zeros  # noqa: F401  (sym.sparse.zeros == dense zeros)

__all__ = ["cast_storage", "retain", "dot", "zeros", "add_n"]

cast_storage = make_symbol_op_func(_registry.get_op("cast_storage"),
                                   "cast_storage")
retain = make_symbol_op_func(_registry.get_op("_sparse_retain"), "retain")
dot = make_symbol_op_func(_registry.get_op("dot"), "dot")
add_n = make_symbol_op_func(_registry.get_op("add_n"), "add_n")
