"""Shape inference over symbol graphs.

TPU-native equivalent of the reference InferShape pass
(ref: src/executor/infer_graph_attr_pass.cc): forward-propagates shapes in
topo order. Per-op output shapes come from `jax.eval_shape` of the
registered pure function (XLA's abstract evaluation does the per-op rules
the reference registers as FInferShape); unknown PARAMETER shapes are
deduced first from data shapes via the hint table below (the reference's
backward-inference for weights).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..ops import registry as _registry

__all__ = ["infer_shape"]


def _pairify(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _hint_param_shapes(node, in_shapes):
    """Deduce parameter-input shapes from the data shape + attrs.
    in_shapes: {input_name: shape or None}. Returns updates dict."""
    op = node.op
    a = node.attrs
    data = in_shapes.get("x") or in_shapes.get("data")
    out = {}
    if data is None:
        return out
    if op == "FullyConnected":
        nh = int(a.get("num_hidden"))
        flatten = a.get("flatten", True)
        in_units = int(_np.prod(data[1:])) if flatten else data[-1]
        out["weight"] = (nh, in_units)
        out["bias"] = (nh,)
    elif op in ("Convolution", "Deconvolution"):
        kernel = a.get("kernel")
        nd = len(kernel) if kernel is not None else len(data) - 2
        kernel = _pairify(kernel, nd)
        nf = int(a.get("num_filter"))
        g = int(a.get("num_group", 1))
        cin = data[1]
        if op == "Convolution":
            out["weight"] = (nf, cin // g) + kernel
        else:
            out["weight"] = (cin, nf // g) + kernel
        out["bias"] = (nf,)
    elif op in ("BatchNorm", "InstanceNorm", "GroupNorm"):
        axis = int(a.get("axis", 1))
        c = data[axis % len(data)]
        for nm in ("gamma", "beta", "moving_mean", "moving_var"):
            out[nm] = (c,)
    elif op == "LayerNorm":
        axis = int(a.get("axis", -1))
        c = data[axis % len(data)]
        out["gamma"] = (c,)
        out["beta"] = (c,)
    elif op == "Embedding":
        out["weight"] = (int(a.get("input_dim")), int(a.get("output_dim")))
    elif op in ("RNN", "rnn"):
        # packed parameter length + state shapes
        from ..ops.nn import rnn_packed_param_size
        h = int(a.get("state_size"))
        layers = int(a.get("num_layers", 1))
        nd = 2 if a.get("bidirectional") else 1
        out["parameters"] = (rnn_packed_param_size(
            a.get("mode", "lstm"), data[-1], h, layers, nd),)
        out["state"] = (layers * nd, data[1], h)
        out["state_cell"] = (layers * nd, data[1], h)
    return out


def infer_shape(sym, *args, partial=False, **kwargs):
    """Returns (arg_shapes, out_shapes, aux_shapes) in the list orders of
    list_arguments()/list_outputs()/list_auxiliary_states()."""
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    known = {}
    if args:
        assert len(args) <= len(arg_names)
        for n, s in zip(arg_names, args):
            if s is not None:
                known[n] = tuple(s)
    for k, v in kwargs.items():
        if v is not None:
            known[k] = tuple(v)

    nodes = sym._topo()
    # shapes per (node id, out_index)
    shapes = {}
    for node in nodes:
        if node.is_variable():
            s = known.get(node.name) or node._shape or \
                (tuple(node.attrs["__shape__"])
                 if "__shape__" in node.attrs else None)
            shapes[(id(node), 0)] = tuple(s) if s else None

    # pass 1+2: deduce parameter variable shapes from hints, then eval
    for node in nodes:
        if node.is_variable():
            continue
        from .control_flow import CONTROL_FLOW_OPS as _CF
        if node.op in _CF:
            # recurse into subgraphs so parameters used inside loop bodies
            # (auto-created weights etc.) get hint-inferred like the
            # reference's subgraph shape inference
            _cf_propagate_var_hints(node, shapes)
        input_names = node.attrs.get("__input_names__")
        in_shapes = {}
        if input_names:
            for iname, (src, oi) in zip(input_names, node.inputs):
                in_shapes[iname] = shapes.get((id(src), oi))
        hints = _hint_param_shapes(node, in_shapes)
        if input_names:
            for iname, (src, oi) in zip(input_names, node.inputs):
                if shapes.get((id(src), oi)) is None and iname in hints:
                    shapes[(id(src), oi)] = tuple(hints[iname])
        if node.attrs.get("__fused_json__") and any(
                shapes.get((id(src), oi)) is None
                for src, oi in node.inputs):
            # fused subgraph node with unknown inputs: deduce them by
            # running inference on the INNER region graph
            # (ref: subgraph FInferShape runs the inner graph's pass).
            # __fused_json__ is specific to fusion nodes, so this can
            # never collide with control-flow's __subgraph__/_cf_cache.
            if isinstance(node._cf_cache, tuple):
                sub_sym, sub_inputs = node._cf_cache
            else:
                from .symbol import load_json as _load_json
                sub_sym = _load_json(node.attrs["__fused_json__"])
                sub_inputs = list(node.attrs["__fused_inputs__"])
                node._cf_cache = (sub_sym, sub_inputs)
            known_inner = {}
            for iname, (src, oi) in zip(sub_inputs, node.inputs):
                si = shapes.get((id(src), oi))
                if si is not None:
                    known_inner[iname] = si
            try:
                arg_sh, _o, _a = infer_shape(sub_sym, partial=True,
                                             **known_inner)
                by_name = dict(zip(sub_sym.list_arguments(), arg_sh))
            except Exception:  # noqa: BLE001 — fall through to eval
                by_name = {}
            for iname, (src, oi) in zip(sub_inputs, node.inputs):
                if shapes.get((id(src), oi)) is None \
                        and by_name.get(iname) is not None:
                    shapes[(id(src), oi)] = tuple(by_name[iname])
        # now try abstract eval
        ins = [shapes.get((id(src), oi)) for src, oi in node.inputs]
        if any(s is None for s in ins):
            if partial:
                for i in range(node.num_outputs):
                    shapes[(id(node), i)] = None
                continue
            missing = [src.name for (src, oi), s in zip(node.inputs, ins)
                       if s is None]
            raise ValueError("cannot infer shape for inputs %s of %s(%s)"
                             % (missing, node.op, node.name))
        outs = _abstract_eval(node, ins)
        for i, s in enumerate(outs):
            shapes[(id(node), i)] = s

    def var_shape(name):
        for node in nodes:
            if node.is_variable() and node.name == name:
                return shapes.get((id(node), 0))
        return None

    arg_shapes = [var_shape(n) for n in arg_names]
    aux_shapes = [var_shape(n) for n in aux_names]
    out_shapes = [shapes.get((id(node), oi)) for node, oi in sym._outputs]
    return arg_shapes, out_shapes, aux_shapes


def _cf_propagate_var_hints(node, shapes):
    """Run partial shape inference inside a control-flow node's subgraphs
    and write inferred shapes back onto unknown outer input VARIABLES
    (loop-body parameters). Mutates `shapes` in place."""
    from .symbol import load_json
    a = node.attrs
    in_shapes = [shapes.get((id(src), oi)) for src, oi in node.inputs]
    carry_off = int(a.get("__num_data__", 0))
    for js, mapping in zip(a["__subgraph__"], a["__subg_inputs__"]):
        sub = load_json(js)
        kwargs = {}
        for vn, kind, idx in mapping:
            if kind == "slice":
                s = in_shapes[idx]
                if s is not None and len(s) >= 1:
                    kwargs[vn] = tuple(s[1:])
            else:
                src_idx = carry_off + idx if kind == "carry" else idx
                s = in_shapes[src_idx]
                if s is not None:
                    kwargs[vn] = tuple(s)
        try:
            arg_shapes, _, _ = infer_shape(sub, partial=True, **kwargs)
        except Exception:
            continue
        inferred = dict(zip(sub.list_arguments(), arg_shapes))
        for vn, kind, idx in mapping:
            s = inferred.get(vn)
            if s is None:
                continue
            src_idx = carry_off + idx if kind == "carry" else idx
            if kind == "slice" or src_idx >= len(node.inputs):
                continue
            src, oi = node.inputs[src_idx]
            if src.is_variable() and shapes.get((id(src), oi)) is None:
                shapes[(id(src), oi)] = tuple(s)
                in_shapes[src_idx] = tuple(s)


def _abstract_eval(node, in_shapes):
    from .control_flow import CONTROL_FLOW_OPS, lower as _cf_lower
    if node.op in CONTROL_FLOW_OPS:
        structs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]

        def cf(*xs):
            return tuple(_cf_lower(node, list(xs), False,
                                   jax.random.PRNGKey(0))[0])

        out = jax.eval_shape(cf, *structs)
        return [tuple(o.shape) for o in out]
    opdef = _registry.get_op(node.op)
    from ..executor import _fn_params
    params, has_var_kw = _fn_params(opdef)
    # filter to the op signature (shared cache with the executor):
    # node.attrs can carry metadata (AttrScope tags, ctx_group, ...) that
    # must never be fed to the kernel function
    attrs = {k: v for k, v in node.attrs.items()
             if not k.startswith("__") and (has_var_kw or k in params)}
    input_names = node.attrs.get("__input_names__")
    structs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]

    if "key" in params and "key" not in attrs:
        attrs["key"] = jax.random.PRNGKey(0)

    def fn(*xs):
        if input_names:
            kw = dict(zip(input_names, xs))
            kw.update(attrs)
            return opdef.fn(**kw)
        return opdef.fn(*xs, **attrs)

    out = jax.eval_shape(fn, *structs)
    if isinstance(out, (tuple, list)):
        return [tuple(o.shape) for o in out]
    return [tuple(out.shape)]
