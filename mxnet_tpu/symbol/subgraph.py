"""Subgraph partitioning extension seam.

TPU-native analog of the reference's graph-partitioning framework
(ref: src/operator/subgraph/subgraph_property.h SubgraphProperty /
SubgraphSelector, build_subgraph.cc): a property selects a connected
node set by predicate and replaces it with ONE fused node whose
compute is a user-supplied compile function.

On TPU the usual *motivation* (offload to MKLDNN/TensorRT) disappears —
whole-graph XLA already fuses — but the extension seam itself still
matters: it is how a user hands a chosen subgraph to a custom compiler
(a Pallas kernel, an AOT-compiled module, a quantized rewrite) while
the rest of the graph stays on the default path.

Model:

    class MyProperty(SubgraphProperty):
        name = "convbnrelu"
        def select(self, node):            # is this node fusible?
            return node.op in ("Convolution", "BatchNorm", "Activation")
        def compile(self, subgraph, input_names):
            # subgraph: a Symbol over Variables named like the outer
            # graph's inputs; return a jax-traceable callable taking
            # the inputs positionally. Default: jit the interpreted
            # subgraph program.
            return super().compile(subgraph, input_names)

    fused_sym = partition(sym, MyProperty())

Selection grows maximal single-consumer CHAINS of selected nodes (the
conv->bn->relu shape; the reference's default selector also walks
producer/consumer edges). Fused nodes are registered as ordinary ops
(`_subgraph_<prop>_<n>`), so executors, autograd, and hybridization
treat them like built-ins — gradients flow through the compiled
callable via jax autodiff.

Limitation (documented): BatchNorm moving-stat side updates inside a
fused region are frozen (the fused node is a pure function); training
still differentiates correctly through batch statistics.
"""
from __future__ import annotations

import itertools

from .symbol import Symbol, _Node, Variable

__all__ = ["SubgraphProperty", "partition"]

_fused_uid = itertools.count()


class SubgraphProperty:
    """Base property (ref: subgraph_property.h SubgraphProperty)."""

    name = "subgraph"

    def select(self, node):
        """Can `node` start or join a fused region?"""
        raise NotImplementedError

    def select_input(self, node, producer):
        """May the region grow from `node` through `producer`?
        Default: the producer must itself be selectable."""
        return self.select(producer)

    def compile(self, subgraph, input_names):
        """subgraph Symbol + ordered input names -> jax-traceable
        callable over positional input arrays. Override to hand the
        region to a custom compiler; the default interprets the
        subgraph with the standard program evaluator under jit."""
        import jax
        from ..executor import _GraphProgram

        prog = _GraphProgram(subgraph)

        def fused(*arrays, _training=False, key=None):
            values = dict(zip(input_names, arrays))
            if key is None:
                key = jax.random.PRNGKey(0)
            outs, _aux = prog.run(values, _training, key)
            return outs[0] if len(outs) == 1 else tuple(outs)

        return fused


def _consumers(symbol, nodes):
    cons = {}
    for n in nodes:
        for src, _oi in n.inputs:
            cons.setdefault(id(src), []).append(n)
    # graph heads are consumers too: a chain MEMBER that is also an
    # output must not be swallowed into a region (it would leave a
    # duplicate unfused copy feeding the head)
    for src, _oi in symbol._outputs:
        cons.setdefault(id(src), []).append("__head__")
    return cons


def partition(symbol, prop):
    """Replace every maximal selected chain in `symbol` with one fused
    node compiled by `prop` (ref: build_subgraph.cc BuildSubgraph)."""
    from ..ops import registry as _registry

    nodes = symbol._topo()
    cons = _consumers(symbol, nodes)
    selected = {id(n): n for n in nodes
                if not n.is_variable() and prop.select(n)}
    # honor select_input vetoes on growth edges
    assigned = {}
    regions = []
    for n in reversed(nodes):  # start from consumers (chain tails)
        if id(n) not in selected or id(n) in assigned:
            continue
        chain = [n]
        node = n
        while True:
            producers = [src for src, _ in node.inputs
                         if id(src) in selected
                         and id(src) not in assigned
                         and prop.select_input(node, src)]
            growable = [p for p in producers
                        if len(cons.get(id(p), [])) == 1]
            if len(growable) != 1:
                break
            node = growable[0]
            chain.append(node)
        chain.reverse()
        for c in chain:
            assigned[id(c)] = len(regions)
        regions.append(chain)

    if not regions:
        return symbol

    # rebuild the graph bottom-up, swapping fused regions in
    replace = {}   # id(old node) -> (new node, out_index base)

    def mapped(src, oi):
        if id(src) in replace:
            new, base = replace[id(src)]
            return (new, base + oi)
        return (remap.get(id(src), src), oi)

    remap = {}
    region_of = {id(c): i for i, chain in enumerate(regions)
                 for c in chain}
    done_regions = set()
    for n in nodes:
        if id(n) in region_of:
            ridx = region_of[id(n)]
            if ridx in done_regions:
                continue
            chain = regions[ridx]
            if n is not chain[-1]:
                continue  # emit the fused node at the chain TAIL's slot
            done_regions.add(ridx)
            in_chain = {id(c) for c in chain}
            # external inputs, in first-use order
            ext, seen = [], set()
            for c in chain:
                for src, oi in c.inputs:
                    if id(src) in in_chain:
                        continue
                    k = (id(src), oi)
                    if k not in seen:
                        seen.add(k)
                        ext.append((src, oi))
            input_names = ["sg_in_%d" % i for i in range(len(ext))]
            # build the inner subgraph over fresh Variables
            inner_map = {}
            for (src, oi), nm in zip(ext, input_names):
                inner_map[(id(src), oi)] = Variable(nm)._outputs[0]
            for c in chain:
                new_inputs = []
                for src, oi in c.inputs:
                    if id(src) in in_chain:
                        inner, ibase = inner_map[(id(src), 0)][0], 0
                        new_inputs.append((inner, oi))
                    else:
                        new_inputs.append(inner_map[(id(src), oi)])
                inner_node = _Node(c.op, c.name, dict(c.attrs),
                                   new_inputs, c.num_outputs)
                inner_map[(id(c), 0)] = (inner_node, 0)
            tail = chain[-1]
            inner_tail = inner_map[(id(tail), 0)][0]
            # expose ALL tail outputs (a multi-output tail like split/
            # BatchNorm may have external consumers of index > 0)
            sub_sym = Symbol([(inner_tail, i)
                              for i in range(tail.num_outputs)])
            fused_fn = prop.compile(sub_sym, input_names)
            op_name = "_subgraph_%s_%d" % (prop.name, next(_fused_uid))
            _registry.register(op_name, num_inputs=len(ext))(fused_fn)
            fused = _Node(op_name, op_name,
                          {"__fused_subgraph__": prop.name,
                           # serialized inner graph: shape inference
                           # must survive tojson/deepcopy round trips
                           "__fused_json__": sub_sym.tojson(),
                           "__fused_inputs__": list(input_names)},
                          [mapped(src, oi) for src, oi in ext],
                          tail.num_outputs)
            # parsed-cache for inference (rebuilt from the JSON attrs
            # lazily after a round trip; the _cf_cache slot is free on
            # fused nodes — control-flow ops are never fused)
            fused._cf_cache = (sub_sym, list(input_names))
            replace[id(tail)] = (fused, 0)
            continue
        if n.is_variable():
            continue
        new_inputs = [mapped(src, oi) for src, oi in n.inputs]
        if new_inputs != n.inputs:
            nn = _Node(n.op, n.name, dict(n.attrs), new_inputs,
                       n.num_outputs)
            remap[id(n)] = nn

    heads = []
    for node, oi in symbol._outputs:
        if id(node) in replace:
            new, base = replace[id(node)]
            heads.append((new, base + oi))
        else:
            heads.append((remap.get(id(node), node), oi))
    return Symbol(heads)
