"""Symbol: declarative graph nodes compiled to ONE XLA computation at bind.

TPU-native redesign of the reference symbolic layer (ref:
python/mxnet/symbol/symbol.py, nnvm::Symbol/Graph). The reference interprets
the bound graph node-by-node through the engine
(ref: src/executor/graph_executor.cc:1384 RunOps); here `bind` compiles the
whole graph into a single jitted function — the design SURVEY.md §3.3 calls
the natural TPU seam ("one CachedOp == one XLA computation"), applied to the
symbolic API as well.

JSON schema mirrors the reference's nnvm graph json (nodes/arg_nodes/heads,
ref: Symbol.tojson symbol.py:1364) so architecture checkpoints round-trip
structurally.
"""
from __future__ import annotations

import json
import threading

import numpy as _np

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones"]

_name_lock = threading.local()


def _counter():
    if not hasattr(_name_lock, "counts"):
        _name_lock.counts = {}
    return _name_lock.counts


def _auto_name(hint):
    # an active NameManager/Prefix scope takes over naming
    # (ref: python/mxnet/name.py NameManager.current)
    from ..name import current as _current_nm
    nm = _current_nm()
    if nm is not None:
        return nm.get(None, hint)
    counts = _counter()
    idx = counts.get(hint, 0)
    counts[hint] = idx + 1
    return "%s%d" % (hint, idx)


# parameter names that denote graph inputs (tensor-valued) in op signatures
INPUT_PARAM_NAMES = (
    "x", "data", "lhs", "rhs", "weight", "bias", "gamma", "beta",
    "moving_mean", "moving_var", "label", "grid", "indices", "index",
    "condition", "cond", "a", "b", "y", "mu", "sigma", "low", "high",
    "lam", "alpha",
    "loc", "scale", "shape_like", "data1", "data2", "rois", "anchors",
    "cls_pred", "loc_pred", "parameters", "state", "state_cell", "like",
    "sequence_length", "A", "B", "C",
)

# aux-state naming convention (BatchNorm moving stats et al.)
AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean", "running_var")


import itertools

_node_uid = itertools.count()


class _Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "_shape",
                 "uid", "_cf_cache")

    def __init__(self, op, name, attrs=None, inputs=(), num_outputs=1,
                 shape=None):
        self.op = op               # registry op name; None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)  # list[(Symbol's node, out_index)]
        self.num_outputs = num_outputs
        self._shape = shape        # user-annotated shape for variables
        self.uid = next(_node_uid)  # creation order, for subgraph cutting
        self._cf_cache = None      # parsed control-flow subgraph programs

    def is_variable(self):
        return self.op is None


class Symbol:
    """A (multi-)output handle onto graph nodes (ref: symbol.py Symbol)."""

    def __init__(self, outputs):
        # outputs: list[(node, out_index)]
        self._outputs = list(outputs)

    # -- construction helpers ---------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group[%d]" % len(self._outputs))

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for i, (node, oi) in enumerate(self._outputs):
                if node.name == idx:
                    return Symbol([self._outputs[i]])
            raise ValueError("no output named %r" % idx)
        out = self._outputs[idx]
        if isinstance(idx, slice):
            return Symbol(out)
        return Symbol([out])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # -- graph traversal ---------------------------------------------------
    def _topo(self):
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = True
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def list_arguments(self):
        """Free variables in topo order, aux excluded (ref: symbol.py)."""
        return [n.name for n in self._topo() if n.is_variable()
                and not n.name.endswith(AUX_SUFFIXES)]

    def list_auxiliary_states(self):
        return [n.name for n in self._topo() if n.is_variable()
                and n.name.endswith(AUX_SUFFIXES)]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable()]

    def list_outputs(self):
        names = []
        for node, oi in self._outputs:
            if node.num_outputs > 1:
                names.append("%s_output%d" % (node.name, oi))
            else:
                names.append("%s_output" % node.name)
        return names

    def get_internals(self):
        outs = []
        for n in self._topo():
            if not n.is_variable():
                for i in range(n.num_outputs):
                    outs.append((n, i))
            else:
                outs.append((n, 0))
        return Symbol(outs)

    def get_children(self):
        kids = []
        for node, _ in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    @property
    def attr_dict(self):
        return {n.name: dict(n.attrs) for n in self._topo()}

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(
            {k: str(v) for k, v in kwargs.items()})

    # -- composition --------------------------------------------------------
    def __call__(self, *args, **kwargs):
        raise NotImplementedError("composition via call is not supported; "
                                  "pass symbols as op arguments")

    # arithmetic (mirrors ndarray ops on symbols)
    def __add__(self, other):
        return _binop("elemwise_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binop("elemwise_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _binop("_rminus_scalar", None, self, other, swap=True)

    def __mul__(self, other):
        return _binop("elemwise_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binop("elemwise_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _binop("_rdiv_scalar", None, self, other, swap=True)

    def __pow__(self, other):
        return _binop("_power", "_power_scalar", self, other)

    def __neg__(self):
        return self.__mul__(-1.0)

    def __mod__(self, other):
        return _binop("mod", "_mod_scalar", self, other)

    def __eq__(self, other):
        if other is None:
            return False
        return _binop("equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        if other is None:
            return True
        return _binop("not_equal", "_not_equal_scalar", self, other)

    def __gt__(self, other):
        return _binop("greater", "_greater_scalar", self, other)

    def __ge__(self, other):
        return _binop("greater_equal", "_greater_equal_scalar", self, other)

    def __lt__(self, other):
        return _binop("lesser", "_lesser_scalar", self, other)

    def __le__(self, other):
        return _binop("lesser_equal", "_lesser_equal_scalar", self, other)

    __hash__ = object.__hash__

    def __bool__(self):
        # ref: symbol.py:123 — a Symbol has no runtime value to branch on;
        # use sym.contrib.cond / lax-lowered control flow instead
        raise TypeError("Symbol cannot be used in boolean context; it has "
                        "no value until bound (use sym.contrib.cond)")

    def __getattr__(self, name):
        # registry ops as methods (`s.sum()`, `s.reshape(...)`), like the
        # reference's generated Symbol methods (ref: symbol/register.py)
        if name.startswith("_"):
            raise AttributeError(name)
        from ..ops import registry as _reg
        try:
            _reg.get_op(name)
        except KeyError:
            raise AttributeError("Symbol has no attribute %r" % name)
        from .register import make_symbol_op_func
        fn = make_symbol_op_func(_reg.get_op(name), name)

        def method(*args, **kwargs):
            return fn(self, *args, **kwargs)

        method.__name__ = name
        return method

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        from .infer import infer_shape as _infer
        return _infer(self, *args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        from .infer import infer_shape as _infer
        return _infer(self, partial=True, *args, **kwargs)

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        dt = _np.float32
        return ([kwargs.get(a, dt) for a in args], [dt] * len(self._outputs),
                [dt] * len(self.list_auxiliary_states()))

    # -- serialization ------------------------------------------------------
    def tojson(self):
        nodes = self._topo()
        index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable() else n.op,
                "name": n.name,
                "attrs": {k: json.dumps(v) for k, v in n.attrs.items()},
                "inputs": [[index[id(src)], oi, 0] for src, oi in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable()]
        heads = [[index[id(node)], oi, 0] for node, oi in self._outputs]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": arg_nodes,
            "heads": heads,
            "attrs": {"mxnet_tpu_version": [1, "1.6.0.tpu1"]},
        }, indent=2)

    def save(self, fname):
        # atomic publication: a crash mid-write must not leave a
        # truncated -symbol.json next to a valid .params file
        from ..base import atomic_write
        with atomic_write(fname, "w") as f:
            f.write(self.tojson())

    # -- evaluation / binding ----------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from ..executor import Executor
        exe = self.bind(ctx, args=kwargs)
        return exe.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """group2ctx maps AttrScope ctx_group names to Contexts for
        model parallelism (ref: graph_executor.cc:388 ctx_map); see
        executor._GraphProgram for the placement semantics."""
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, **kwargs):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    group2ctx=group2ctx, **kwargs)

    # convenience used by module/model code
    def debug_str(self):
        lines = []
        for n in self._topo():
            kind = "Variable" if n.is_variable() else n.op
            lines.append("%s %s <- %s" % (kind, n.name,
                                          [s.name for s, _ in n.inputs]))
        return "\n".join(lines)


def _binop(op_name, scalar_op, lhs, rhs, swap=False):
    from .register import create_symbol_op
    if isinstance(rhs, Symbol):
        return create_symbol_op(op_name, [lhs, rhs], {})
    # scalar path
    if swap:
        return create_symbol_op(op_name, [lhs], {"scalar": float(rhs)})
    return create_symbol_op(scalar_op, [lhs], {"scalar": float(rhs)})


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """ref: symbol.py var/Variable."""
    from ..attribute import apply as _attr_apply
    attrs = _attr_apply(attr)
    if shape is not None:
        attrs["__shape__"] = list(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    if init is not None:
        # serialized so it survives tojson round-trips; honored by
        # Initializer.__call__ (ref: symbol.py var() __init__ attr)
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    if lr_mult is not None:
        attrs["__lr_mult__"] = float(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = float(wd_mult)
    node = _Node(None, name, attrs, shape=tuple(shape) if shape else None)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _parse_attr_value(v):
    """Attr values come in three dialects: this framework's tojson
    (JSON-encoded), the reference 1.x dmlc strings ("(3, 3)", "False",
    "64"), and plain strings ("relu"). Try them in that order
    (ref: src/nnvm/legacy_json_util.cc does the same normalization)."""
    if not isinstance(v, str):
        return v
    try:
        return json.loads(v)
    except (ValueError, TypeError):
        pass
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def load_json(json_str):
    """Parse a symbol JSON — this framework's own output, the
    reference's 1.x format (`attrs`, 3-tuple inputs, mxnet_version
    attr), or the pre-1.0 legacy format (`param` + `attr` per node,
    2-tuple inputs; ref: src/nnvm/legacy_json_util.cc UpgradeJSON_*).
    Compat is proven against fixture files emitted by real MXNet
    (tests/fixtures/ref_mxnet_*_symbol.json)."""
    g = json.loads(json_str)
    nodes = []
    for jn in g["nodes"]:
        raw = dict(jn.get("attrs") or jn.get("param") or {})
        attrs = {k: _parse_attr_value(v) for k, v in raw.items()}
        # legacy per-node metadata (ctx_group/lr_mult/wd_mult...) rides
        # in "attr"; keep it out of kernel kwargs via the __-prefix
        for k, v in (jn.get("attr") or {}).items():
            attrs.setdefault("__%s__" % k, v)
        if jn["op"] == "null":
            node = _Node(None, jn["name"], attrs)
        else:
            node = _Node(jn["op"], jn["name"], attrs)
        nodes.append(node)
    for jn, node in zip(g["nodes"], nodes):
        node.inputs = [(nodes[e[0]], e[1]) for e in jn["inputs"]]
        if not node.is_variable():
            node.num_outputs = _num_outputs_of(node)
            if node.op in ("BatchNorm", "batch_norm") \
                    and len(node.inputs) == 3:
                # pre-1.0 BatchNorm had implicit moving stats; the
                # reference's JSON upgrade adds the aux inputs
                # (ref: src/nnvm/legacy_json_util.cc UpgradeJSON_000800)
                for suffix in ("moving_mean", "moving_var"):
                    aux = _Node(None, "%s_%s" % (node.name, suffix))
                    node.inputs.append((aux, 0))
            if "__input_names__" not in node.attrs:
                # reference JSON carries no input-name metadata; recover
                # it from the op signature so parameter-shape hinting
                # works on loaded graphs (ref: nnvm op FListInputNames)
                from .register import op_input_names
                from ..ops import registry as _registry
                try:
                    names = op_input_names(_registry.get_op(node.op))
                except KeyError:
                    names = None
                if names and len(names) >= len(node.inputs):
                    node.attrs["__input_names__"] = \
                        list(names[:len(node.inputs)])
    return Symbol([(nodes[e[0]], e[1]) for e in g["heads"]])


def _num_outputs_of(node):
    # multi-output ops known to the framework; attr-dependent counts
    # mirror the reference's per-op FNumOutputs (ref: nnvm op registry)
    if "__num_outputs__" in node.attrs:
        return int(node.attrs["__num_outputs__"])
    if node.op in ("BatchNorm", "batch_norm"):
        return 3
    if node.op in ("split", "SliceChannel"):
        return int(node.attrs.get("num_outputs", 1))
    if node.op in ("RNN", "rnn"):
        if node.attrs.get("state_outputs"):
            return 3 if node.attrs.get("mode", "lstm") == "lstm" else 2
        return 1
    if node.op == "moments":
        return 2
    if node.op == "topk":
        return 2 if node.attrs.get("ret_typ") == "both" else 1
    from ..ops import registry as _reg
    try:
        declared = _reg.get_op(node.op).num_outputs
    except KeyError:
        declared = None
    if declared is not None:
        return declared(node.attrs) if callable(declared) else int(declared)
    return 1


def zeros(shape, dtype="float32", name=None, **kwargs):
    from .register import create_symbol_op
    return create_symbol_op("_zeros", [], {"shape": shape, "dtype": dtype},
                            name=name)


def ones(shape, dtype="float32", name=None, **kwargs):
    from .register import create_symbol_op
    return create_symbol_op("_ones", [], {"shape": shape, "dtype": dtype},
                            name=name)
