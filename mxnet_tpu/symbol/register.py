"""Symbol-level op wrappers generated from the functional registry.

Mirrors the reference's import-time symbol wrapper generation
(ref: python/mxnet/symbol/register.py): every registered op gets a function
accepting Symbols (positional or by keyword), auto-creating weight/bias
Variables it needs (reference behavior for missing param inputs), and
returning a new Symbol node.
"""
from __future__ import annotations

import inspect

from ..ops import registry as _registry
from .symbol import Symbol, _Node, _auto_name, Variable, INPUT_PARAM_NAMES

__all__ = ["populate", "create_symbol_op", "op_input_names"]

_INPUT_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of per-op input-name lists; deterministic, duplicate insert benign)


def op_input_names(opdef):
    """Ordered tensor-input parameter names of an op fn; None if variadic."""
    if opdef.input_names is not None:
        return list(opdef.input_names)
    if opdef.name in _INPUT_CACHE:
        return _INPUT_CACHE[opdef.name]
    sig = inspect.signature(opdef.fn)
    names = []
    variadic = False
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            variadic = True
            break
        if p.name in INPUT_PARAM_NAMES:
            names.append(p.name)
        elif p.name in ("key", "_training"):
            continue
        else:
            # first non-input, non-special param ends the input prefix
            break
    res = None if variadic else names
    _INPUT_CACHE[opdef.name] = res
    return res


def _scoped_name(name, hint):
    """Node naming through the active NameManager/Prefix: explicit names
    also pass through it, so Prefix('net_') prefixes them like the
    reference (ref: python/mxnet/name.py NameManager.get)."""
    from ..name import current as _current_nm
    nm = _current_nm()
    if nm is not None:
        return nm.get(name, hint)
    return name or _auto_name(hint)


def create_symbol_op(op_name, sym_inputs, attrs, name=None):
    """Build a Symbol node for `op_name` with the given input Symbols."""
    opdef = _registry.get_op(op_name)
    node_name = _scoped_name(name, opdef.name.lower())
    inputs = []
    for s in sym_inputs:
        assert isinstance(s, Symbol), type(s)
        assert len(s._outputs) == 1, "op inputs must be single-output symbols"
        inputs.append(s._outputs[0])
    from ..attribute import apply as _attr_apply
    attrs = _attr_apply(attrs)
    node = _Node(opdef.name, node_name, attrs, inputs)
    from .symbol import _num_outputs_of
    node.num_outputs = _num_outputs_of(node)
    return Symbol([(node, 0)])


def make_symbol_op_func(opdef, public_name):
    input_names = op_input_names(opdef)

    def op_func(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        node_name = _scoped_name(name, opdef.name.lower())
        sym_inputs = []
        attrs = {}
        if input_names is None:
            # variadic op: all positional Symbol args are inputs
            for a in args:
                if isinstance(a, Symbol):
                    sym_inputs.append(a)
                else:
                    raise TypeError("positional args must be Symbols")
            for k, v in kwargs.items():
                if isinstance(v, Symbol):
                    sym_inputs.append(v)
                else:
                    attrs[k] = v
        else:
            # the reference's docs/wrappers spell the first input `data`
            # while many registry fns name it `x` (and vice versa) —
            # accept either spelling (ref: generated op wrappers accept
            # the documented name)
            for given, actual in (("data", "x"), ("x", "data")):
                if given in kwargs and given not in input_names \
                        and actual in input_names and actual not in kwargs:
                    kwargs[actual] = kwargs.pop(given)
            provided = {}
            pos = list(args)
            for iname in input_names:
                if iname in kwargs:
                    provided[iname] = kwargs.pop(iname)
                elif pos:
                    provided[iname] = pos.pop(0)
            # remaining kwargs are static attrs; a Symbol under a name the
            # op doesn't declare as an input would be silently dropped
            # from the graph — make that an error instead
            for k, v in kwargs.items():
                if isinstance(v, Symbol):
                    if k not in input_names:
                        raise TypeError(
                            "%s got Symbol for unknown input %r "
                            "(inputs: %s)" % (public_name, k, input_names))
                    provided[k] = v
                else:
                    attrs[k] = v
            no_bias = bool(attrs.get("no_bias", False))
            for iname in input_names:
                v = provided.get(iname)
                if v is None and iname in provided:
                    # explicit None (e.g. bias=None passed positionally)
                    # must not survive into the input list
                    del provided[iname]
                if v is None:
                    if iname == "bias" and no_bias:
                        continue
                    if iname in ("label",):
                        v = Variable("%s_%s" % (node_name, iname))
                    elif iname in ("weight", "bias", "gamma", "beta",
                                   "moving_mean", "moving_var"):
                        # auto-created parameter variable (ref behavior)
                        v = Variable("%s_%s" % (node_name, iname))
                    else:
                        continue
                if not isinstance(v, Symbol):
                    raise TypeError("input %s must be a Symbol, got %s"
                                    % (iname, type(v)))
                provided[iname] = v
            if any(isinstance(p, Symbol) for p in pos):
                raise TypeError(
                    "%s got %d unexpected positional Symbol input(s) "
                    "beyond its declared inputs %s"
                    % (public_name, sum(isinstance(p, Symbol) for p in pos),
                       input_names))
            sym_inputs = [provided[i] for i in input_names if i in provided]
            attrs["__input_names__"] = [i for i in input_names
                                        if i in provided]
        inputs = []
        for s in sym_inputs:
            assert len(s._outputs) == 1, \
                "op inputs must be single-output symbols"
            inputs.append(s._outputs[0])
        from ..attribute import apply as _attr_apply
        merged = _attr_apply(None)
        merged.update(attrs)           # op params
        if attr:
            merged.update(attr)        # explicit per-call attrs win
        attrs = merged
        node = _Node(opdef.name, node_name, attrs, inputs)
        from .symbol import _num_outputs_of
        node.num_outputs = _num_outputs_of(node)
        # BatchNorm exposes one visible output in symbolic graphs (the
        # reference's NumVisibleOutputs=1 — mean/var are internal); other
        # multi-output ops return a group symbol so unpacking works
        if node.op in ("BatchNorm", "batch_norm"):
            return Symbol([(node, 0)])
        return Symbol([(node, i) for i in range(node.num_outputs)])

    op_func.__name__ = public_name
    op_func.__doc__ = opdef.fn.__doc__
    return op_func


def populate(namespace_dict):
    for name in _registry.list_ops():
        opdef = _registry.get_op(name)
        if name not in namespace_dict:
            namespace_dict[name] = make_symbol_op_func(opdef, name)
