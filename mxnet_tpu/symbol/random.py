"""``mx.sym.random`` namespace (ref: python/mxnet/symbol/random.py —
generated there from the same registry as nd.random; same here).

Scalar hyperparameters become node attrs (`_random_*` ops); Symbol
hyperparameters switch to the per-element `_sample_*` form, mirroring
the reference's dispatch."""
from __future__ import annotations

from .register import create_symbol_op
from .symbol import Symbol

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial",
           "multinomial", "randint", "shuffle"]


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def _dist(scalar_op, sample_op, params, shape, dtype, name=None):
    """params: ordered (name, value) hyperparameters."""
    if any(isinstance(v, Symbol) for _, v in params):
        return create_symbol_op(sample_op, [v for _, v in params],
                                {"shape": _shape(shape), "dtype": dtype},
                                name=name)
    attrs = {k: v for k, v in params}
    attrs.update({"shape": _shape(shape), "dtype": dtype})
    return create_symbol_op(scalar_op, [], attrs, name=name)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", name=None, **kw):
    return _dist("random_uniform", "sample_uniform",
                 [("low", low), ("high", high)], shape, dtype, name)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", name=None, **kw):
    return _dist("random_normal", "sample_normal",
                 [("loc", loc), ("scale", scale)], shape, dtype, name)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", name=None, **kw):
    return normal(loc=loc, scale=scale, shape=shape or None, dtype=dtype,
                  name=name)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", name=None, **kw):
    return _dist("random_gamma", "sample_gamma",
                 [("alpha", alpha), ("beta", beta)], shape, dtype, name)


def exponential(scale=1.0, shape=None, dtype="float32", name=None, **kw):
    return _dist("random_exponential", "sample_exponential",
                 [("lam", 1.0 / scale if not isinstance(scale, Symbol)
                   else 1.0 / scale)], shape, dtype, name)


def poisson(lam=1.0, shape=None, dtype="float32", name=None, **kw):
    return _dist("random_poisson", "sample_poisson", [("lam", lam)],
                 shape, dtype, name)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", name=None,
                      **kw):
    return _dist("random_negative_binomial", "sample_negative_binomial",
                 [("k", k), ("p", p)], shape, dtype, name)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", name=None, **kw):
    return _dist("random_generalized_negative_binomial",
                 "sample_generalized_negative_binomial",
                 [("mu", mu), ("alpha", alpha)], shape, dtype, name)


def multinomial(data, shape=None, get_prob=False, dtype="int32", name=None,
                **kw):
    return create_symbol_op("sample_multinomial", [data],
                            {"shape": _shape(shape), "get_prob": get_prob,
                             "dtype": dtype}, name=name)


def randint(low, high, shape=None, dtype="int32", name=None, **kw):
    return _dist("random_randint", "random_randint",
                 [("low", low), ("high", high)], shape, dtype, name)


def shuffle(data, name=None, **kw):
    return create_symbol_op("shuffle", [data], {}, name=name)
