"""Symbolic graph API (ref: python/mxnet/symbol/__init__.py)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     zeros, ones)
from . import symbol as _symbol_mod
from .register import populate as _populate

_populate(globals())

from . import contrib  # noqa: E402  (after populate: contrib uses registry)

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "contrib"]
