"""Symbolic graph API (ref: python/mxnet/symbol/__init__.py)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     zeros, ones)
from . import symbol as _symbol_mod
from .register import populate as _populate

_populate(globals())

from . import contrib  # noqa: E402  (after populate: contrib uses registry)
from . import random  # noqa: E402  (sub-namespaces mirror nd.<ns>)
from . import linalg  # noqa: E402
from . import image  # noqa: E402
from . import sparse  # noqa: E402


def Custom(*args, **kwargs):
    """Symbolic custom-op node; lowers to a jax.pure_callback island in
    the compiled graph (ref: python/mxnet/operator.py sym.Custom)."""
    from .. import operator as _op_mod  # registers the "Custom" graph op
    from ..ops import registry as _r
    from .register import make_symbol_op_func
    assert _op_mod is not None
    return make_symbol_op_func(_r.get_op("Custom"), "Custom")(
        *args, **kwargs)


__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "contrib", "Custom"]
