"""``mx.sym.linalg`` namespace (ref: python/mxnet/symbol/linalg.py —
generated from the same `linalg_*` registry entries as nd.linalg)."""
from __future__ import annotations

from ..ops import registry as _registry
from .register import make_symbol_op_func

__all__ = []


def _populate_linalg():
    g = globals()
    for name in _registry.list_ops():
        if name.startswith("linalg_") and not name.startswith("linalg__"):
            short = name[len("linalg_"):]
            if short not in g:
                g[short] = make_symbol_op_func(_registry.get_op(name),
                                               short)
                __all__.append(short)


_populate_linalg()
