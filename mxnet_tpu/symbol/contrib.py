"""mx.sym.contrib — symbol-level control flow + contrib ops.

ref: python/mxnet/symbol/contrib.py (foreach :212, while_loop :375,
cond :598). The reference cuts the Python-built subgraph out of the trace
and hands it to stateful C++ subgraph ops; here the captured subgraph is
embedded in the node and the executor lowers it to `lax.scan` /
`lax.while_loop` / `lax.cond` inside the single bound XLA program
(see symbol/control_flow.py).
"""
from __future__ import annotations

from .symbol import Symbol, Variable
from .register import _scoped_name, make_symbol_op_func
from .control_flow import capture_subgraph, next_marker
from .symbol import _Node

__all__ = ["foreach", "while_loop", "cond"]


def _flatten(x, what):
    if isinstance(x, Symbol):
        return [x], 0
    if not isinstance(x, (list, tuple)):
        raise TypeError("%s must be a Symbol or nested list of Symbols, "
                        "got %s" % (what, type(x)))
    flat, fmt = [], []
    for i in x:
        f, s = _flatten(i, what)
        flat.extend(f)
        fmt.append(s)
    return flat, fmt


def _regroup(flat, fmt):
    if fmt == 0:
        return flat[0], flat[1:]
    out = []
    for s in fmt:
        v, flat = _regroup(flat, s)
        out.append(v)
    return out, flat


def _single_out(sym, what):
    if len(sym._outputs) != 1:
        raise ValueError("%s must be single-output symbols" % what)
    return sym._outputs[0]


def _node_outputs(node, n):
    return [Symbol([(node, i)]) for i in range(n)]


def foreach(body, data, init_states, name="foreach"):
    """Scan `body(data_t, states) -> (out, new_states)` over axis 0 of
    `data`, stacking outputs (ref: symbol/contrib.py:212 foreach).
    Lowered to `lax.scan` in the bound program."""
    node_name = _scoped_name(name if name != "foreach" else None, "foreach")
    flat_data, data_fmt = _flatten(data, "foreach data")
    if not flat_data:
        raise ValueError("foreach requires at least one input sequence")
    flat_states, state_fmt = _flatten(init_states, "foreach init_states")

    marker = next_marker()
    data_ph = [Variable("%s_data%d" % (node_name, i))
               for i in range(len(flat_data))]
    state_ph = [Variable("%s_state%d" % (node_name, i))
                for i in range(len(flat_states))]
    data_arg, _ = _regroup(data_ph, data_fmt)
    state_arg, _ = _regroup(state_ph, state_fmt)
    outs, new_states = body(data_arg, state_arg)

    flat_out, out_fmt = _flatten([] if outs is None else outs, "foreach out")
    flat_nst, _ = _flatten(new_states, "foreach new_states")
    if len(flat_nst) != len(flat_states):
        raise ValueError("body must return as many states as init_states "
                         "(%d vs %d)" % (len(flat_nst), len(flat_states)))

    placeholders = {}
    roles = {}
    for i, s in enumerate(data_ph):
        n = s._outputs[0][0]
        placeholders[id(n)] = n.name
        roles[n.name] = ("slice", i)
    for j, s in enumerate(state_ph):
        n = s._outputs[0][0]
        placeholders[id(n)] = n.name
        roles[n.name] = ("carry", j)

    heads = [_single_out(s, "foreach outputs") for s in flat_out + flat_nst]
    js, input_names, cuts = capture_subgraph(heads, placeholders, marker)

    n_fixed = len(flat_data) + len(flat_states)
    mapping = []
    for k, vn in enumerate(input_names):
        if vn in roles:
            kind, idx = roles[vn]
            mapping.append([vn, kind, idx])
        else:
            mapping.append([vn, "input",
                            n_fixed + (k - len(placeholders))])

    node_inputs = ([_single_out(s, "foreach data") for s in flat_data]
                   + [_single_out(s, "foreach states") for s in flat_states]
                   + cuts)
    total = len(flat_out) + len(flat_states)
    attrs = {
        "__subgraph__": [js],
        "__subg_inputs__": [mapping],
        "__num_data__": len(flat_data),
        "__num_states__": len(flat_states),
        "__num_out_data__": len(flat_out),
        "__num_outputs__": total,
    }
    node = _Node("_foreach", node_name, attrs, node_inputs,
                 num_outputs=max(total, 1))
    outs_syms = _node_outputs(node, total)
    out_res, rest = _regroup(outs_syms[:len(flat_out)], out_fmt) \
        if flat_out else ([], outs_syms)
    st_res, _ = _regroup(outs_syms[len(flat_out):], state_fmt) \
        if flat_states else ([], [])
    return out_res, st_res


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """`while cond(*loop_vars): step_out, loop_vars = func(*loop_vars)`,
    outputs stacked and zero-padded to `max_iterations`
    (ref: symbol/contrib.py:375 while_loop). Lowered to
    `lax.while_loop` with preallocated output buffers."""
    if max_iterations is None:
        raise ValueError("max_iterations must be provided")
    max_iterations = int(max_iterations)
    if max_iterations <= 0:
        raise ValueError("max_iterations must be positive")
    node_name = _scoped_name(name if name != "while_loop" else None,
                             "while_loop")
    flat_vars, var_fmt = _flatten(loop_vars, "while_loop loop_vars")
    if not flat_vars:
        raise ValueError("while_loop requires at least one loop var")

    marker = next_marker()
    var_ph = [Variable("%s_var%d" % (node_name, i))
              for i in range(len(flat_vars))]
    var_arg, _ = _regroup(var_ph, var_fmt)
    var_args = var_arg if isinstance(var_arg, list) else [var_arg]

    pred = cond(*var_args)
    step_out, new_vars = func(*var_args)
    flat_out, out_fmt = _flatten([] if step_out is None else step_out,
                                 "while_loop step_output")
    flat_nv, _ = _flatten(new_vars, "while_loop new_loop_vars")
    if len(flat_nv) != len(flat_vars):
        raise ValueError("func must return as many loop_vars as it takes "
                         "(%d vs %d)" % (len(flat_nv), len(flat_vars)))

    placeholders = {}
    roles = {}
    for j, s in enumerate(var_ph):
        n = s._outputs[0][0]
        placeholders[id(n)] = n.name
        roles[n.name] = ("carry", j)

    js_c, names_c, cuts_c = capture_subgraph(
        [_single_out(pred, "while_loop cond")], placeholders, marker)
    heads_b = [_single_out(s, "while_loop outputs")
               for s in flat_out + flat_nv]
    js_b, names_b, cuts_b = capture_subgraph(heads_b, placeholders, marker)

    # merge closure cuts of both subgraphs into one node-input list
    node_inputs = [_single_out(s, "while_loop loop_vars")
                   for s in flat_vars]
    cut_index = {}
    for src, oi in cuts_c + cuts_b:
        if (id(src), oi) not in cut_index:
            cut_index[(id(src), oi)] = len(node_inputs)
            node_inputs.append((src, oi))

    def mapping_of(input_names, cuts):
        m = []
        ci = iter(cuts)
        for vn in input_names:
            if vn in roles:
                kind, idx = roles[vn]
                m.append([vn, kind, idx])
            else:
                src, oi = next(ci)
                m.append([vn, "input", cut_index[(id(src), oi)]])
        return m

    total = len(flat_out) + len(flat_vars)
    attrs = {
        "__subgraph__": [js_c, js_b],
        "__subg_inputs__": [mapping_of(names_c, cuts_c),
                            mapping_of(names_b, cuts_b)],
        "__num_vars__": len(flat_vars),
        "__num_out_data__": len(flat_out),
        "__num_outputs__": total,
        "max_iterations": max_iterations,
    }
    node = _Node("_while_loop", node_name, attrs, node_inputs,
                 num_outputs=max(total, 1))
    outs_syms = _node_outputs(node, total)
    out_res, _ = _regroup(outs_syms[:len(flat_out)], out_fmt) \
        if flat_out else ([], [])
    var_res, _ = _regroup(outs_syms[len(flat_out):], var_fmt)
    return out_res, var_res


def cond(pred, then_func, else_func, name="cond"):
    """Run one of two subgraphs on a scalar predicate Symbol
    (ref: symbol/contrib.py:598 cond). Lowered to `lax.cond`."""
    node_name = _scoped_name(name if name != "cond" else None, "cond")

    marker = next_marker()
    p = pred
    t = then_func()
    e = else_func()
    flat_t, t_fmt = _flatten(t, "cond then outputs")
    flat_e, _ = _flatten(e, "cond else outputs")
    if len(flat_t) != len(flat_e):
        raise ValueError("then_func and else_func must return the same "
                         "number of outputs (%d vs %d)"
                         % (len(flat_t), len(flat_e)))

    js_p, names_p, cuts_p = capture_subgraph(
        [_single_out(p, "cond pred")], {}, marker)
    js_t, names_t, cuts_t = capture_subgraph(
        [_single_out(s, "cond then") for s in flat_t], {}, marker)
    js_e, names_e, cuts_e = capture_subgraph(
        [_single_out(s, "cond else") for s in flat_e], {}, marker)

    node_inputs = []
    cut_index = {}
    for src, oi in cuts_p + cuts_t + cuts_e:
        if (id(src), oi) not in cut_index:
            cut_index[(id(src), oi)] = len(node_inputs)
            node_inputs.append((src, oi))

    def mapping_of(input_names, cuts):
        m = []
        ci = iter(cuts)
        for vn in input_names:
            src, oi = next(ci)
            m.append([vn, "input", cut_index[(id(src), oi)]])
        return m

    total = len(flat_t)
    attrs = {
        "__subgraph__": [js_p, js_t, js_e],
        "__subg_inputs__": [mapping_of(names_p, cuts_p),
                            mapping_of(names_t, cuts_t),
                            mapping_of(names_e, cuts_e)],
        "__num_outputs__": total,
    }
    node = _Node("_cond", node_name, attrs, node_inputs,
                 num_outputs=max(total, 1))
    outs_syms = _node_outputs(node, total)
    res, _ = _regroup(outs_syms, t_fmt)
    return res


# curated contrib op surface, mirroring nd.contrib (boolean_mask,
# arange_like, quantize, ...) via the shared registry
def _expose(*names):
    from ..ops import registry as _registry
    for n in names:
        try:
            opdef = _registry.get_op(n)
        except Exception:
            continue
        globals()[n] = make_symbol_op_func(opdef, n)
        __all__.append(n)


_expose("boolean_mask", "arange_like", "quantize", "dequantize",
        "quantize_v2", "div_sqrt_dim", "index_copy", "index_array",
        "getnnz", "edge_id", "interleaved_matmul_selfatt_qk",
        "interleaved_matmul_selfatt_valatt")
