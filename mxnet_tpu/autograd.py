"""Autograd: imperative tape over per-op ``jax.vjp`` closures.

TPU-native re-design of the reference's autograd (ref: python/mxnet/autograd.py,
src/imperative/imperative.cc:40-330 Imperative::InvokeOp/RecordOp/Backward).
The reference stores an nnvm tape node per recorded op and replays a gradient
graph; here each recorded op captures its own ``jax.vjp`` closure (residuals
live on device), and ``backward`` walks the Python tape in reverse topological
order. Under a hybridized block one whole jitted computation appears as a
single tape node, which is the ``CachedOp`` analog
(ref: src/imperative/cached_op.cc:231 CachedOp::Gradient).
"""
from __future__ import annotations

import threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as _np

from . import profiler as _profiler

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "mark_variables",
           "backward", "grad", "deliver_grad", "get_symbol", "Function"]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording():
    """ref: autograd.is_recording (python/mxnet/autograd.py:84)."""
    return _STATE.recording


def is_training():
    """ref: autograd.is_training (python/mxnet/autograd.py:94)."""
    return _STATE.training


def set_recording(is_record):
    prev = _STATE.recording
    _STATE.recording = bool(is_record)
    return prev


def set_training(train_mode):
    prev = _STATE.training
    _STATE.training = bool(train_mode)
    return prev


class _RecordingStateScope:
    """Scope guard flipping (recording, training) like the reference's
    _RecordingStateScope (python/mxnet/autograd.py:37)."""

    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, *args):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode=True):
    """Record ops for autograd. ref: python/mxnet/autograd.py:122."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """Stop recording inside a record scope. ref: python/mxnet/autograd.py:146."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    """ref: python/mxnet/autograd.py:168."""
    return _RecordingStateScope(None, True)


def predict_mode():
    """ref: python/mxnet/autograd.py:188."""
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape graph. A Node is one recorded op; NDArrays produced while recording
# carry ``_autograd_entry = (node, output_index)``. Analog of AGInfo on nnvm
# nodes (ref: include/mxnet/imperative.h:42-77).
# ---------------------------------------------------------------------------

class Node:
    __slots__ = ("inputs", "vjp_fn", "num_outputs", "name", "saved_entries",
                 "out_shapes", "out_dtypes", "fwd_fn", "in_datas")

    def __init__(self, inputs, vjp_fn, num_outputs, name, out_shapes, out_dtypes):
        self.inputs = inputs              # list[NDArray] (op's array inputs)
        self.vjp_fn = vjp_fn              # cotangents(tuple) -> input cotangents
        self.num_outputs = num_outputs
        self.name = name
        # entries of the inputs at record time (an input may later be detached)
        self.saved_entries = [getattr(a, "_autograd_entry", None) for a in inputs]
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.fwd_fn = None                # pure replay fn (for create_graph)
        self.in_datas = [a._data for a in inputs]  # record-time input buffers


def record_op(name, out_arrays, input_ndarrays, vjp_fn):
    """Attach a tape node to the freshly produced output NDArrays.

    Called by the generated op wrappers (ndarray/register.py) when
    ``is_recording()``; analog of Imperative::RecordOp
    (ref: src/imperative/imperative.cc:193).
    """
    node = Node(list(input_ndarrays), vjp_fn, len(out_arrays), name,
                [a.shape for a in out_arrays], [a.dtype for a in out_arrays])
    for i, arr in enumerate(out_arrays):
        arr._autograd_entry = (node, i)
    return node


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: autograd.mark_variables (python/mxnet/autograd.py:217)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, gradient, req in zip(variables, gradients, grad_reqs):
        var._grad = gradient if req != "null" else None
        var._grad_req = req
        var._autograd_entry = None


def _toposort(heads):
    """Topological order (producers before consumers) of reachable Nodes,
    via iterative post-order DFS."""
    order, emitted, visiting = [], set(), set()
    stack = []
    for h in heads:
        entry = getattr(h, "_autograd_entry", None)
        if entry is not None:
            stack.append((entry[0], False))
    while stack:
        node, children_done = stack.pop()
        if id(node) in emitted:
            continue
        if children_done:
            emitted.add(id(node))
            order.append(node)
            continue
        if id(node) in visiting:
            continue
        visiting.add(id(node))
        stack.append((node, True))
        for e in node.saved_entries:
            if e is not None and id(e[0]) not in emitted:
                stack.append((e[0], False))
    return order  # children before parents; iterate reversed for backward


def _zeros_cotangent(shape, dtype):
    return jnp.zeros(shape, dtype)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. all marked variables on the tape.

    ref: autograd.backward (python/mxnet/autograd.py:246) →
    Imperative::Backward (src/imperative/imperative.cc:280).
    """
    from .ndarray import NDArray  # local import to avoid cycle

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(heads) != len(head_grads):
        raise ValueError("heads and head_grads must have the same length")

    t0 = _time.perf_counter() if _profiler._LIVE else None
    grads = _run_backward(heads, head_grads, retain_graph)
    if t0 is not None:
        _profiler.record_op("autograd.backward",
                            (_time.perf_counter() - t0) * 1e6,
                            category="autograd", lane="autograd",
                            args={"heads": len(heads)})

    # accumulate into .grad of marked leaves
    for var, g in grads.items():
        deliver_grad(var, g)
    return None


def deliver_grad(var, g):
    """Write one computed cotangent into ``var``'s grad buffer honoring
    its grad_req (write/add) and mark the grad fresh — the accumulation
    step of the tape sweep, shared with the gluon fused train step so
    both paths materialize gradients identically (stale-grad tracking:
    Trainer clears the flag after each update; ref: NDArray fresh_grad,
    src/ndarray/ndarray.cc)."""
    if var._grad is None:
        return
    if getattr(var, "_grad_req", "write") == "add":
        var._grad._data = var._grad._data + g
    else:
        var._grad._data = g.astype(var._grad._data.dtype)
    var._fresh_grad = True


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients w.r.t. ``variables`` instead of accumulating into
    ``.grad``. ref: autograd.grad (python/mxnet/autograd.py:273).

    ``create_graph=True`` re-records the backward pass so higher-order
    gradients work (ref: test_higher_order_grad.py coverage).
    """
    from .ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if retain_graph is None:
        retain_graph = create_graph

    if create_graph:
        return _grad_create_graph(heads, variables, head_grads, single)

    t0 = _time.perf_counter() if _profiler._LIVE else None
    grads = _run_backward(heads, head_grads, retain_graph,
                          targets=variables)
    if t0 is not None:
        _profiler.record_op("autograd.grad",
                            (_time.perf_counter() - t0) * 1e6,
                            category="autograd", lane="autograd",
                            args={"heads": len(heads),
                                  "variables": len(variables)})
    out = []
    for v in variables:
        g = grads.get(v)
        if g is None:
            g = jnp.zeros(v.shape, v.dtype)
        out.append(NDArray(g, ctx=v.context))
    return out[0] if single else out


def _grad_create_graph(heads, variables, head_grads, single):
    """Differentiable grad for higher-order autograd: replay the recorded
    subgraph as one pure function G(variables) -> heads, then take
    ``jax.vjp`` of the *gradient* function so the returned grads carry a tape
    node whose vjp differentiates through the backward pass
    (ref coverage: tests/python/unittest/test_higher_order_grad.py)."""
    from .ndarray import NDArray

    order = _toposort(heads)
    for node in order:
        if node.fwd_fn is None:
            raise RuntimeError(
                "create_graph=True requires the full tape (an op is missing "
                "its replay function: %s)" % node.name)

    var_ids = {id(v): i for i, v in enumerate(variables)}

    def replay_heads(*var_datas):
        env = {}  # (id(node), idx) -> data

        def lookup(arr, entry):
            if entry is not None and (id(entry[0]), entry[1]) in env:
                return env[(id(entry[0]), entry[1])]
            if id(arr) in var_ids:
                return var_datas[var_ids[id(arr)]]
            return arr._data

        for node in order:
            ins = [lookup(a, e) for a, e in zip(node.inputs, node.saved_entries)]
            outs = node.fwd_fn(*ins)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
        return tuple(lookup(h, getattr(h, "_autograd_entry", None))
                     for h in heads)

    seeds = tuple(
        (hg._data if isinstance(hg, NDArray) else jnp.asarray(hg))
        if hg is not None else jnp.ones(h.shape, h.dtype)
        for h, hg in zip(heads, head_grads))

    def grad_fn(*var_datas):
        outs, vjp = jax.vjp(replay_heads, *var_datas)
        g = vjp(seeds)
        # single-output convention: bare array, matching how backward() calls
        # vjp_fn(cts[0]) for num_outputs == 1
        return g[0] if len(variables) == 1 else g

    var_datas = tuple(v._data for v in variables)
    if is_recording():
        g_datas, vjp2 = jax.vjp(grad_fn, *var_datas)
        raw = [g_datas] if len(variables) == 1 else list(g_datas)
        outs = [NDArray(g) for g in raw]
        node = record_op("grad", outs, list(variables), vjp2)
        node.fwd_fn = grad_fn
    else:
        g_datas = grad_fn(*var_datas)
        raw = [g_datas] if len(variables) == 1 else list(g_datas)
        outs = [NDArray(g) for g in raw]
    return outs[0] if single else outs


def _run_backward(heads, head_grads, retain_graph, targets=None):
    """Shared reverse sweep. Returns {leaf NDArray: cotangent jax array}."""
    from .ndarray import NDArray
    from .ndarray import register as _register

    # tape grad is a bulk sync point (ISSUE: CachedOp seam): pending
    # segment ops may feed marked leaves or heads — run them first
    _register.flush_bulk_segment()

    order = _toposort(heads)
    if not order:
        # heads are leaves; gradient of head w.r.t itself is head_grad
        result = {}
        for h, hg in zip(heads, head_grads):
            if h._grad is not None or (targets is not None and any(h is t for t in targets)):
                seed = hg._data if hg is not None else jnp.ones(h.shape, h.dtype)
                result[h] = seed
        if not result and targets is None:
            raise ValueError("cannot differentiate: outputs are not on the "
                             "recorded tape (did you forget autograd.record()?)")
        return result

    # cotangent storage: per node output slot, plus per leaf NDArray
    node_cts = {}  # id(node) -> [ct or None] * num_outputs
    leaf_cts = {}  # NDArray -> ct
    id2node = {id(n): n for n in order}

    def _seed(arr, ct):
        entry = getattr(arr, "_autograd_entry", None)
        if entry is not None and id(entry[0]) in id2node:
            node, idx = entry
            slots = node_cts.setdefault(id(node), [None] * node.num_outputs)
            slots[idx] = ct if slots[idx] is None else slots[idx] + ct
        else:
            leaf_cts[arr] = ct if arr not in leaf_cts else leaf_cts[arr] + ct

    for h, hg in zip(heads, head_grads):
        if hg is not None:
            seed = hg._data if isinstance(hg, NDArray) else jnp.asarray(hg)
        else:
            seed = jnp.ones(h.shape, h.dtype)
        _seed(h, seed)

    for node in reversed(order):
        slots = node_cts.get(id(node))
        if slots is None:
            continue
        cts = tuple(
            slots[i] if slots[i] is not None
            else _zeros_cotangent(node.out_shapes[i], node.out_dtypes[i])
            for i in range(node.num_outputs))
        in_cts = node.vjp_fn(cts if node.num_outputs > 1 else cts[0])
        if not isinstance(in_cts, (tuple, list)):
            in_cts = (in_cts,)
        for inp, entry, ct in zip(node.inputs, node.saved_entries, in_cts):
            if ct is None:
                continue
            ctd = ct._data if hasattr(ct, "_data") else ct
            if ctd.dtype == jax.dtypes.float0:
                continue
            if entry is not None and id(entry[0]) in id2node:
                n2, idx = entry
                slots2 = node_cts.setdefault(id(n2), [None] * n2.num_outputs)
                slots2[idx] = ctd if slots2[idx] is None else slots2[idx] + ctd
            else:
                prev = leaf_cts.get(inp)
                leaf_cts[inp] = ctd if prev is None else prev + ctd

    if not retain_graph:
        for h in heads:
            h._autograd_entry = None
        for node in order:
            node.vjp_fn = None
            node.inputs = []
            node.saved_entries = []
            node.in_datas = []

    return leaf_cts


def get_symbol(x):
    """The reference returns the recorded Symbol (python/mxnet/autograd.py:304);
    here the tape has no nnvm graph — export via symbol tracing instead."""
    raise NotImplementedError(
        "get_symbol: use mxnet_tpu.symbol tracing (hybridize/export) instead")


class Function:
    """User-defined differentiable function, ref: autograd.Function
    (python/mxnet/autograd.py:368).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def __call__(self, *inputs):
        from .ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            def vjp_fn(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                with pause():
                    in_grads = func.backward(
                        *[NDArray(c) for c in cts])
                if isinstance(in_grads, NDArray):
                    in_grads = [in_grads]
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in in_grads)

            record_op(type(self).__name__, outs, list(inputs), vjp_fn)
        return outputs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
