"""Checkpoint helpers for the legacy RNN package
(ref: python/mxnet/rnn/rnn.py): fused weights are unpacked to per-gate
entries on save so checkpoints are readable/portable, and re-packed on
load so the `RNN` op's single parameter vector is restored."""
from __future__ import annotations

import warnings

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["rnn_unroll", "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def rnn_unroll(cell, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC"):
    """Deprecated alias for cell.unroll (ref: rnn/rnn.py:26)."""
    warnings.warn("rnn_unroll is deprecated. Call cell.unroll directly.")
    return cell.unroll(length=length, inputs=inputs,
                       begin_state=begin_state, layout=layout)


def _as_cells(cells):
    return [cells] if isinstance(cells, BaseRNNCell) else list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """save_checkpoint with fused weights unpacked (ref: rnn/rnn.py:32)."""
    for cell in _as_cells(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint with weights re-packed (ref: rnn/rnn.py:62)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cells(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback that saves unpacked checkpoints
    (ref: rnn/rnn.py:97)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
