"""Legacy symbolic RNN cell API (the pre-Gluon `mx.rnn` package).

API parity with the reference (ref: python/mxnet/rnn/rnn_cell.py:108
BaseRNNCell and subclasses), built on this framework's Symbol IR.

TPU design notes:
- A stepwise ``unroll`` builds one static symbol graph; the executor
  traces it into a SINGLE fused XLA program, so per-step Python cost is
  bind-time only and the MXU sees batched i2h/h2h matmuls per step.
- ``FusedRNNCell`` lowers to the registry ``RNN`` op (ops/nn.py:706),
  whose per-layer recurrence is a lax.scan — one XLA while loop, no
  per-step dispatch — the TPU analog of the reference's cuDNN path.
- The reference defers the batch dimension of initial states by giving
  them shape ``(0, H)`` and relying on bidirectional shape inference.
  XLA needs static shapes, so ``unroll`` rewrites constant-op begin
  states into ``broadcast_like`` graphs that derive the batch size from
  the input symbol (same observable behavior, forward-only inference).
"""
from __future__ import annotations

import warnings

from .. import initializer as init
from .. import ndarray
from .. import symbol

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "BaseConvRNNCell", "ConvRNNCell",
           "ConvLSTMCell", "ConvGRUCell"]


class RNNParams(object):
    """Weight-sharing container: name -> Variable, all prefixed
    (ref: rnn_cell.py:78)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize between a merged Symbol and a per-step list
    (ref: rnn_cell.py:51 _normalize_sequence). Returns (inputs, t_axis)."""
    assert inputs is not None, \
        "unroll(inputs=None) is not supported; create input variables " \
        "outside unroll"
    t_axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else t_axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            assert len(inputs.list_outputs()) == 1, \
                "grouped symbols cannot be unrolled; pass list(inputs)"
            inputs = list(symbol.split(inputs, axis=in_axis,
                                       num_outputs=length, squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=t_axis) for i in inputs]
            inputs = symbol.concat(*inputs, dim=t_axis)
            in_axis = t_axis
    if isinstance(inputs, symbol.Symbol) and t_axis != in_axis:
        inputs = symbol.swapaxes(inputs, dim1=t_axis, dim2=in_axis)
    return inputs, t_axis


_DEFERRED_STATE_OPS = ("_zeros", "_ones")


def _concretize_states(states, ref, ref_batch_axis):
    """Replace deferred-batch constant states (shape contains 0) with
    ``broadcast_like`` graphs deriving the batch size from ``ref``.

    The reference leaves batch as 0 and lets bidirectional shape
    inference fill it (ref: rnn_cell.py:190 begin_state); XLA-side
    inference is forward-only, so the batch dim must come from a symbol
    that has it."""
    out = []
    for st in states:
        if isinstance(st, (list, tuple)):
            out.append(_concretize_states(st, ref, ref_batch_axis))
            continue
        node = st._outputs[0][0]
        shape = tuple(node.attrs.get("shape") or ())
        if node.op in _DEFERRED_STATE_OPS and 0 in shape:
            if shape.count(0) != 1:
                raise ValueError("begin_state shape %s has more than one "
                                 "deferred dim" % (shape,))
            b_axis = shape.index(0)
            base_shape = tuple(1 if i == b_axis else d
                               for i, d in enumerate(shape))
            maker = symbol.zeros if node.op == "_zeros" else symbol.ones
            base = maker(shape=base_shape)
            st = symbol.broadcast_like(base, ref, lhs_axes=(b_axis,),
                                       rhs_axes=(ref_batch_axis,),
                                       name=node.name)
        out.append(st)
    return out


class BaseRNNCell(object):
    """Abstract stepwise RNN cell (ref: rnn_cell.py:108)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in getattr(self, "_cells", []):
            cell.reset()

    def __call__(self, inputs, states):
        """One time step: (output, new_states)."""
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Initial states. With the default ``func=symbol.zeros`` the
        batch dim stays deferred (0) until ``unroll`` concretizes it;
        pass ``func=symbol.Variable`` to feed states as inputs."""
        assert not self._modified, \
            "cannot call begin_state on a cell wrapped by a modifier; " \
            "call it on the modifier cell"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if info is not None:
                kwargs.update(info)
            states.append(func(name=name, **kwargs))
        return states

    # -- fused<->per-gate weight translation --------------------------------
    def unpack_weights(self, args):
        """Split each fused i2h/h2h matrix into per-gate entries
        (ref: rnn_cell.py:225)."""
        args = args.copy()
        gates = self._gate_names
        if not gates:
            return args
        h = self._num_hidden
        for grp in ("i2h", "h2h"):
            w = args.pop("%s%s_weight" % (self._prefix, grp))
            b = args.pop("%s%s_bias" % (self._prefix, grp))
            for j, gate in enumerate(gates):
                args["%s%s%s_weight" % (self._prefix, grp, gate)] = \
                    w[j * h:(j + 1) * h].copy()
                args["%s%s%s_bias" % (self._prefix, grp, gate)] = \
                    b[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (ref: rnn_cell.py:265)."""
        args = args.copy()
        gates = self._gate_names
        if not gates:
            return args
        for grp in ("i2h", "h2h"):
            ws, bs = [], []
            for gate in gates:
                ws.append(args.pop("%s%s%s_weight"
                                   % (self._prefix, grp, gate)))
                bs.append(args.pop("%s%s%s_bias" % (self._prefix, grp, gate)))
            args["%s%s_weight" % (self._prefix, grp)] = \
                ndarray.concatenate(ws)
            args["%s%s_bias" % (self._prefix, grp)] = ndarray.concatenate(bs)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll across time; the resulting graph compiles to one XLA
        program at bind (ref: rnn_cell.py:295)."""
        self.reset()
        inputs, _ = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        states = _concretize_states(begin_state, inputs[0], 0)
        outputs = []
        for t in range(length):
            output, states = self(inputs[t], states)
            outputs.append(output)
        outputs, _ = _format_sequence(length, outputs, layout, merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: out = act(i2h + h2h) (ref: rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order [i, f, c, o] (ref: rnn_cell.py:408)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias", init=init.LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        gates = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden * 4, name="%si2h" % name) \
            + symbol.FullyConnected(
                data=states[0], weight=self._hW, bias=self._hB,
                num_hidden=self._num_hidden * 4, name="%sh2h" % name)
        gi, gf, gc, go = symbol.SliceChannel(gates, num_outputs=4,
                                             name="%sslice" % name)
        in_gate = symbol.Activation(gi, act_type="sigmoid", name="%si" % name)
        forget = symbol.Activation(gf, act_type="sigmoid", name="%sf" % name)
        cand = symbol.Activation(gc, act_type="tanh", name="%sc" % name)
        out_gate = symbol.Activation(go, act_type="sigmoid",
                                     name="%so" % name)
        next_c = symbol.elemwise_add(forget * states[1], in_gate * cand,
                                     name="%sstate" % name)
        next_h = symbol.elemwise_mul(
            out_gate, symbol.Activation(next_c, act_type="tanh"),
            name="%sout" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """cuDNN-variant GRU, gate order [r, z, o] (ref: rnn_cell.py:469)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%s_i2h" % name)
        h2h = symbol.FullyConnected(data=prev, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%s_h2h" % name)
        ir, iz, inew = symbol.SliceChannel(i2h, num_outputs=3,
                                           name="%s_i2h_slice" % name)
        hr, hz, hnew = symbol.SliceChannel(h2h, num_outputs=3,
                                           name="%s_h2h_slice" % name)
        reset = symbol.Activation(ir + hr, act_type="sigmoid",
                                  name="%s_r_act" % name)
        update = symbol.Activation(iz + hz, act_type="sigmoid",
                                   name="%s_z_act" % name)
        cand = symbol.Activation(inew + reset * hnew, act_type="tanh",
                                 name="%s_h_act" % name)
        next_h = symbol.elemwise_add((1.0 - update) * cand, update * prev,
                                     name="%sout" % name)
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the `RNN` op — the TPU analog of
    the reference's cuDNN path: one lax.scan per layer/direction instead
    of per-step symbols (ref: rnn_cell.py:536)."""

    _GATE_NAMES = {"rnn_relu": ("",), "rnn_tanh": ("",),
                   "lstm": ("_i", "_f", "_c", "_o"),
                   "gru": ("_r", "_z", "_o")}

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get(
            "parameters", init=init.FusedRNN(
                None, num_hidden, num_layers, mode, bidirectional,
                forget_bias))

    @property
    def state_info(self):
        ld = self._num_layers * (2 if self._bidirectional else 1)
        n_states = 2 if self._mode == "lstm" else 1
        return [{"shape": (ld, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n_states)]

    @property
    def _gate_names(self):
        return self._GATE_NAMES[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _slice_weights(self, arr, li, lh):
        """Per-gate views into the packed vector; layout matches
        ops/nn.py _rnn_unpack_params (weights layer-major, direction
        inner, then biases) = the reference's cuDNN layout
        (ref: rnn_cell.py:600)."""
        args = {}
        gates = self._gate_names
        dirs = self._directions
        b = len(dirs)
        p = 0
        for layer in range(self._num_layers):
            isz = li if layer == 0 else b * lh
            for d in dirs:
                for grp, cols in (("i2h", isz), ("h2h", lh)):
                    for gate in gates:
                        name = "%s%s%d_%s%s_weight" % (self._prefix, d,
                                                       layer, grp, gate)
                        args[name] = arr[p:p + lh * cols].reshape((lh, cols))
                        p += lh * cols
        for layer in range(self._num_layers):
            for d in dirs:
                for grp in ("i2h", "h2h"):
                    for gate in gates:
                        name = "%s%s%d_%s%s_bias" % (self._prefix, d,
                                                     layer, grp, gate)
                        args[name] = arr[p:p + lh]
                        p += lh
        assert p == arr.size, "invalid fused parameter size"
        return args

    def unpack_weights(self, args):
        args = args.copy()
        packed = args.pop(self._parameter.name)
        host = packed.asnumpy() if isinstance(packed, ndarray.NDArray) \
            else packed
        from ..ops.nn import rnn_packed_input_size
        h = self._num_hidden
        num_input = rnn_packed_input_size(
            host.size, self._mode, h, self._num_layers,
            len(self._directions))
        for name, w in self._slice_weights(host, num_input, h).items():
            args[name] = ndarray.array(w.copy())
        return args

    def pack_weights(self, args):
        # assembled in a host numpy buffer (slices write through there;
        # device arrays are immutable), placed on device once at the end
        from ..ops.nn import rnn_packed_param_size
        args = args.copy()
        h = self._num_hidden
        w0 = args["%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])]
        total = rnn_packed_param_size(self._mode, w0.shape[1], h,
                                      self._num_layers,
                                      len(self._directions))
        import numpy as _np
        host = _np.zeros((total,), dtype=str(w0.dtype))
        for name, w in self._slice_weights(host, w0.shape[1], h).items():
            v = args.pop(name)
            w[:] = v.asnumpy() if isinstance(v, ndarray.NDArray) else v
        args[self._parameter.name] = ndarray.array(host)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _format_sequence(length, inputs, layout, True)
        if axis == 1:
            warnings.warn("NTC layout detected. Consider using TNC for "
                          "FusedRNNCell for faster speed")
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        else:
            assert axis == 0, "unsupported layout %s" % layout
        if begin_state is None:
            begin_state = self.begin_state()
        # inputs is TNC here: batch rides axis 1
        states = _concretize_states(begin_state, inputs, 1)
        kwargs = {"state": states[0]}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional,
                         p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **kwargs)
        if not self._get_next_state:
            outputs, states = rnn, []
        else:
            outs = list(rnn)
            for s in outs[1:]:
                s._set_attr(__layout__="LNC")
            outputs, states = outs[0], outs[1:]
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        outputs, _ = _format_sequence(length, outputs, layout, merge_outputs)
        return outputs, states

    def unfuse(self):
        """Equivalent stack of stepwise cells (ref: rnn_cell.py:714)."""
        cell_of = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        stack = SequentialRNNCell()
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    cell_of("%sl%d_" % (self._prefix, i)),
                    cell_of("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(cell_of("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


def _cells_state_info(cells):
    return sum((c.state_info for c in cells), [])


def _cells_begin_state(cells, **kwargs):
    return sum((c.begin_state(**kwargs) for c in cells), [])


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order (ref: rnn_cell.py:748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "specify params for SequentialRNNCell or children, not both"
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified, \
            "cannot call begin_state on a modifier-wrapped cell"
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell), \
                "BidirectionalCell cannot be stepped"
            n = len(cell.state_info)
            inputs, sub = cell(inputs, states[p:p + n])
            p += n
            next_states.extend(sub)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if begin_state is None:
            begin_state = self.begin_state()
        p = 0
        next_states = []
        last = len(self._cells) - 1
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=begin_state[p:p + n],
                layout=layout,
                merge_outputs=None if i < last else merge_outputs)
            p += n
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout-on-input cell (ref: rnn_cell.py:827)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        assert isinstance(dropout, (int, float)), \
            "dropout probability must be a number"
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _format_sequence(length, inputs, layout, merge_outputs)
        if isinstance(inputs, symbol.Symbol):
            return self(inputs, [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(BaseRNNCell):
    """Wraps a base cell to alter its behavior; parameters stay with the
    base cell (ref: rnn_cell.py:867)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified, \
            "cannot call begin_state on a modifier-wrapped cell"
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout on outputs/states (ref: rnn_cell.py:909)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout; unfuse() first"
        assert not isinstance(base_cell, BidirectionalCell), \
            "apply ZoneoutCell to the cells inside a BidirectionalCell"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev = self.prev_output
        if prev is None:
            prev = symbol.zeros_like(next_output)
        output = next_output
        if self.zoneout_outputs != 0.:
            output = symbol.where(mask(self.zoneout_outputs, next_output),
                                  next_output, prev)
        if self.zoneout_states != 0.:
            next_states = [
                symbol.where(mask(self.zoneout_states, ns), ns, os)
                for ns, os in zip(next_states, states)]
        self.prev_output = output
        return output, next_states


class ResidualCell(ModifierCell):
    """output = base(output) + input (ref: rnn_cell.py:957)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs,
                                     name="%s_plus_residual" % output.name)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        if merge_outputs is None:
            merge_outputs = isinstance(outputs, symbol.Symbol)
        inputs, _ = _format_sequence(length, inputs, layout, merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(
                outputs, inputs, name="%s_plus_residual" % outputs.name)
        else:
            outputs = [symbol.elemwise_add(o, i,
                                           name="%s_plus_residual" % o.name)
                       for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Unrolls l_cell forward and r_cell backward, concatenating outputs
    (ref: rnn_cell.py:998)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, \
                "specify params for BidirectionalCell or children, not both"
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified, \
            "cannot call begin_state on a modifier-wrapped cell"
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        states = _concretize_states(begin_state, inputs[0], 0)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout,
            merge_outputs=merge_outputs)
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, symbol.Symbol) \
                and isinstance(r_outputs, symbol.Symbol)
            if not merge_outputs:
                if isinstance(l_outputs, symbol.Symbol):
                    l_outputs = list(symbol.SliceChannel(
                        l_outputs, axis=axis, num_outputs=length,
                        squeeze_axis=1))
                if isinstance(r_outputs, symbol.Symbol):
                    r_outputs = list(symbol.SliceChannel(
                        r_outputs, axis=axis, num_outputs=length,
                        squeeze_axis=1))
        if merge_outputs:
            l_outputs = [l_outputs]
            r_outputs = [symbol.reverse(r_outputs, axis=axis)]
        else:
            r_outputs = list(reversed(r_outputs))
        outputs = []
        for i, (lo, ro) in enumerate(zip(l_outputs, r_outputs)):
            nm = "%sout" % self._output_prefix if merge_outputs \
                else "%st%d" % (self._output_prefix, i)
            outputs.append(symbol.concat(lo, ro, dim=1 + merge_outputs,
                                         name=nm))
        if merge_outputs:
            outputs = outputs[0]
        return outputs, [l_states, r_states]


class BaseConvRNNCell(BaseRNNCell):
    """Convolutional RNN base: i2h/h2h are convolutions over spatial
    state maps (ref: rnn_cell.py:1094)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel, h2h_dilate,
                 i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                 i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer,
                 activation, prefix="", params=None, conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        assert h2h_kernel[0] % 2 == 1 and h2h_kernel[1] % 2 == 1, \
            "h2h kernel dims must be odd, got %s" % str(h2h_kernel)
        self._h2h_kernel = h2h_kernel
        self._h2h_pad = (h2h_dilate[0] * (h2h_kernel[0] - 1) // 2,
                         h2h_dilate[1] * (h2h_kernel[1] - 1) // 2)
        self._h2h_dilate = h2h_dilate
        self._i2h_kernel = i2h_kernel
        self._i2h_stride = i2h_stride
        self._i2h_pad = i2h_pad
        self._i2h_dilate = i2h_dilate
        self._num_hidden = num_hidden
        self._input_shape = input_shape
        self._conv_layout = conv_layout
        self._activation = activation
        # state spatial dims = i2h conv output dims, batch deferred
        probe = symbol.Convolution(
            data=symbol.Variable("_probe_data"), num_filter=num_hidden,
            kernel=i2h_kernel, stride=i2h_stride, pad=i2h_pad,
            dilate=i2h_dilate, layout=conv_layout)
        out_shape = probe.infer_shape(_probe_data=input_shape)[1][0]
        self._state_shape = (0,) + tuple(out_shape[1:])
        self._iW = self.params.get("i2h_weight",
                                   init=i2h_weight_initializer)
        self._hW = self.params.get("h2h_weight",
                                   init=h2h_weight_initializer)
        self._iB = self.params.get("i2h_bias", init=i2h_bias_initializer)
        self._hB = self.params.get("h2h_bias", init=h2h_bias_initializer)

    @property
    def _num_gates(self):
        return len(self._gate_names)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout},
                {"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def _conv_forward(self, inputs, states, name):
        i2h = symbol.Convolution(
            data=inputs, num_filter=self._num_hidden * self._num_gates,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate, weight=self._iW,
            bias=self._iB, layout=self._conv_layout, name="%si2h" % name)
        h2h = symbol.Convolution(
            data=states[0], num_filter=self._num_hidden * self._num_gates,
            kernel=self._h2h_kernel, stride=(1, 1), pad=self._h2h_pad,
            dilate=self._h2h_dilate, weight=self._hW, bias=self._hB,
            layout=self._conv_layout, name="%sh2h" % name)
        return i2h, h2h

    def __call__(self, inputs, states):
        raise NotImplementedError("BaseConvRNNCell is abstract")


class ConvRNNCell(BaseConvRNNCell):
    """Conv RNN cell (ref: rnn_cell.py:1176)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer=None, h2h_bias_initializer=None,
                 activation="tanh", prefix="ConvRNN_", params=None,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class ConvLSTMCell(BaseConvRNNCell):
    """Conv LSTM (Shi et al. 2015) (ref: rnn_cell.py:1253)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer=None, h2h_bias_initializer=None,
                 activation="tanh", prefix="ConvLSTM_", params=None,
                 forget_bias=1.0, conv_layout="NCHW"):
        if i2h_bias_initializer is None:
            i2h_bias_initializer = init.LSTMBias(forget_bias=forget_bias)
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        gates = i2h + h2h
        c_axis = self._conv_layout.find("C")
        gi, gf, gc, go = symbol.SliceChannel(gates, num_outputs=4,
                                             axis=c_axis,
                                             name="%sslice" % name)
        in_gate = symbol.Activation(gi, act_type="sigmoid",
                                    name="%si" % name)
        forget = symbol.Activation(gf, act_type="sigmoid",
                                   name="%sf" % name)
        cand = self._get_activation(gc, self._activation, name="%sc" % name)
        out_gate = symbol.Activation(go, act_type="sigmoid",
                                     name="%so" % name)
        next_c = symbol.elemwise_add(forget * states[1], in_gate * cand,
                                     name="%sstate" % name)
        next_h = symbol.elemwise_mul(
            out_gate, self._get_activation(next_c, self._activation),
            name="%sout" % name)
        return next_h, [next_h, next_c]


class ConvGRUCell(BaseConvRNNCell):
    """Conv GRU (ref: rnn_cell.py:1349)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer=None, h2h_bias_initializer=None,
                 activation="tanh", prefix="ConvGRU_", params=None,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         activation, prefix, params, conv_layout)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        c_axis = self._conv_layout.find("C")
        ir, iz, inew = symbol.SliceChannel(i2h, num_outputs=3, axis=c_axis,
                                           name="%s_i2h_slice" % name)
        hr, hz, hnew = symbol.SliceChannel(h2h, num_outputs=3, axis=c_axis,
                                           name="%s_h2h_slice" % name)
        reset = symbol.Activation(ir + hr, act_type="sigmoid",
                                  name="%s_r_act" % name)
        update = symbol.Activation(iz + hz, act_type="sigmoid",
                                   name="%s_z_act" % name)
        cand = self._get_activation(inew + reset * hnew, self._activation,
                                    name="%s_h_act" % name)
        next_h = symbol.elemwise_add((1.0 - update) * cand,
                                     update * states[0],
                                     name="%sout" % name)
        return next_h, [next_h]
