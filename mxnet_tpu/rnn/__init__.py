"""Legacy symbolic RNN API — `mx.rnn` (ref: python/mxnet/rnn/__init__.py).

Cells build Symbol graphs (compiled to one XLA program at bind);
FusedRNNCell rides the lax.scan-backed `RNN` op. See rnn_cell.py for the
TPU design notes."""
from .rnn_cell import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
