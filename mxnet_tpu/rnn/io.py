"""Bucketing data iterator + vocab helpers for the legacy RNN package
(ref: python/mxnet/rnn/io.py)."""
from __future__ import annotations

import bisect
import random

import numpy as np

from .. import ndarray
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map token lists to int lists, growing the vocab for unseen tokens
    (ref: rnn/io.py:30)."""
    new_vocab = vocab is None
    if new_vocab:
        vocab = {invalid_key: invalid_label}
    idx = start_label
    encoded = []
    for sent in sentences:
        row = []
        for word in sent:
            if word not in vocab:
                assert new_vocab or unknown_token, \
                    "unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                if unknown_token:
                    word = unknown_token
                vocab[word] = idx
                idx += 1
            row.append(vocab[word])
        encoded.append(row)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Language-model iterator that pads each sentence to its bucket and
    yields (data, next-token-label) batches keyed by bucket
    (ref: rnn/io.py:84). Bucketing keeps the shape set small so the XLA
    jit cache holds one compiled program per bucket (SURVEY long-seq
    strategy)."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [i for i, c in enumerate(counts) if c >= batch_size]
        buckets = sorted(buckets)

        per_bucket = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            row = np.full((buckets[buck],), invalid_label, dtype=dtype)
            row[:len(sent)] = sent
            per_bucket[buck].append(row)
        # drop empty buckets so every batch shape actually occurs
        keep = [i for i, rows in enumerate(per_bucket) if rows]
        self.buckets = [buckets[i] for i in keep]
        self.data = [np.asarray(per_bucket[i], dtype=dtype) for i in keep]
        if ndiscard:
            print("WARNING: discarded %d sentences longer than the largest "
                  "bucket." % ndiscard)

        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        if self.major_axis not in (0, 1):
            raise ValueError("invalid layout %r: use NT or TN" % layout)
        self.default_bucket_key = max(self.buckets)

        def _desc(name):
            shape = (batch_size, self.default_bucket_key) \
                if self.major_axis == 0 \
                else (self.default_bucket_key, batch_size)
            return DataDesc(name=name, shape=shape, layout=layout)

        self.provide_data = [_desc(data_name)]
        self.provide_label = [_desc(label_name)]

        self.idx = []
        for i, rows in enumerate(self.data):
            self.idx.extend(
                (i, j)
                for j in range(0, len(rows) - batch_size + 1, batch_size))
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for rows in self.data:
            np.random.shuffle(rows)
        self.nddata = []
        self.ndlabel = []
        for rows in self.data:
            label = np.empty_like(rows)
            label[:, :-1] = rows[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(ndarray.array(rows, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data = data.T
            label = label.T
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(name=self.data_name, shape=data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(name=self.label_name,
                                    shape=label.shape,
                                    layout=self.layout)])
