"""Runtime kernel compilation (ref: python/mxnet/rtc.py CudaModule,
src/common/rtc.cc NVRTC wrapper).

The reference compiles raw CUDA C at runtime via NVRTC and launches it on
streams. The TPU-native equivalent is Pallas: kernels are Python functions
compiled through Mosaic, so ``PallasModule`` fills the ``CudaModule`` API
slot — construct with kernel functions, get launchable handles, call them
on arrays. ``CudaModule`` itself remains as a guided error for ported
code (CUDA C source cannot target the MXU).
"""
from __future__ import annotations

import jax

from .ndarray.ndarray import NDArray

__all__ = ["CudaModule", "PallasModule"]


class PallasModule:
    """Bundle of named Pallas kernels (API mirror of rtc.py:CudaModule).

    ``kernels`` maps name -> a callable built from ``pl.pallas_call`` (or
    any jax-jittable function). ``get_kernel(name)`` returns a launchable
    whose ``launch(args, ...)`` runs on the attached device —
    grid/block configuration lives inside the pallas_call, where the
    compiler can see it, instead of the launch site like CUDA."""

    def __init__(self, kernels):
        self._kernels = dict(kernels)
        self._compiled = {}

    def get_kernel(self, name, signature=None):
        """signature accepted for CudaModule API compat; shapes/dtypes are
        inferred per call by tracing (ref: rtc.py get_kernel). Kernels are
        cached per name so repeated get_kernel().launch() in a loop hits
        the jit compile cache."""
        kern = self._compiled.get(name)
        if kern is None:
            kern = _Kernel(self._kernels[name], name)
            self._compiled[name] = kern
        return kern

    def names(self):
        return sorted(self._kernels)


class _Kernel:
    """ref: rtc.py CudaKernel.launch."""

    def __init__(self, fn, name):
        # mxlint: disable=MX005 (one jit per user-built CudaModule kernel, compiled at construction; key count == kernel count)
        self._fn = jax.jit(fn)
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """grid/block/shared_mem accepted for API compat and ignored —
        Mosaic owns the schedule (ref: rtc.py launch signature)."""
        datas = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._fn(*datas)
        if isinstance(out, (tuple, list)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    def __call__(self, *args):
        return self.launch(args)


class CudaModule:
    """ref: python/mxnet/rtc.py:CudaModule — raw CUDA C has no TPU
    lowering; port kernels to Pallas and use PallasModule."""

    def __init__(self, source, options=(), exports=()):
        raise NotImplementedError(
            "CudaModule compiles CUDA C via NVRTC, which cannot target "
            "the TPU MXU. Write the kernel with jax.experimental.pallas "
            "and wrap it in mxnet_tpu.rtc.PallasModule (see "
            "mxnet_tpu/pallas_kernels/ for worked examples).")
