"""Shared test utilities, shipped as library code like the reference's
``python/mxnet/test_utils.py`` (ref: test_utils.py:55 default_context,
:364 rand_ndarray, :512 assert_almost_equal, :883 check_numeric_gradient,
:1314 check_consistency).
"""
from __future__ import annotations

import numpy as _np

from .context import current_context, cpu
from . import ndarray as nd
from . import autograd

__all__ = ["default_context", "set_default_context",
           "assert_almost_equal", "rand_ndarray",
           "rand_shape_nd", "rand_shape_2d", "rand_shape_3d",
           "check_numeric_gradient", "check_consistency",
           "check_symbolic_forward", "check_symbolic_backward",
           "almost_equal", "same"]


def default_context():
    return current_context()


def set_default_context(ctx):
    """Make ``ctx`` this thread's default (ref: test_utils.py:68)."""
    from .context import Context
    Context._default_ctx.value = ctx


def rand_shape_nd(ndim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1),
            _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1),
            _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def same(a, b):
    """Exact array equality (ref: test_utils.py same)."""
    a = a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else _np.asarray(b)
    return _np.array_equal(a, b)


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    arr = _np.random.uniform(-1, 1, size=shape).astype(dtype or _np.float32)
    out = nd.array(arr, ctx=ctx)
    if stype != "default":
        return out.tostype(stype)
    return out


def almost_equal(a, b, rtol=1e-5, atol=1e-8):
    return _np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else _np.asarray(b)
    if not _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        idx = _np.unravel_index(_np.argmax(_np.abs(a - b)), a.shape) \
            if a.shape else ()
        raise AssertionError(
            "arrays not almost equal (rtol=%g atol=%g): max |diff| %g at %s\n"
            "%s=%s\n%s=%s" % (rtol, atol, float(_np.max(_np.abs(a - b))), idx,
                              names[0], a, names[1], b))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Finite-difference gradient check of ``fn(*inputs) -> scalar NDArray``.
    ref: test_utils.py:883 check_numeric_gradient."""
    inputs = [x if isinstance(x, nd.NDArray) else nd.array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = fn(*inputs)
    y.backward()
    for x in inputs:
        xa = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(xa)
        flat = xa.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            yp = fn(*[nd.array(a.asnumpy()) if a is not x else
                      nd.array(xa.astype(_np.float32)) for a in inputs])
            fp = float(yp.asnumpy())
            flat[i] = orig - eps
            ym = fn(*[nd.array(a.asnumpy()) if a is not x else
                      nd.array(xa.astype(_np.float32)) for a in inputs])
            fm = float(ym.asnumpy())
            flat[i] = orig
            num.reshape(-1)[i] = (fp - fm) / (2 * eps)
        assert_almost_equal(x.grad.asnumpy(), num.astype(_np.float32),
                            rtol=rtol, atol=atol,
                            names=("autograd", "numeric"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-6):
    """Run ``fn`` under each context and compare outputs — the reference's
    cross-backend validator (ref: test_utils.py:1314). The default
    ctx_list compares the host CPU against the CURRENT context (on an
    accelerator-attached process that is a real cpu-vs-device check;
    cpu-only processes collapse to one context and the comparison is
    vacuous, as in the reference when no GPU is present). The deep
    device sweep with ULP accounting is benchmark/tpu_numerics.py."""
    if ctx_list is None:
        ctx_list = [cpu()]
        if current_context() != cpu():
            ctx_list.append(current_context())
    outs = []
    for ctx in ctx_list:
        with ctx:
            ins = [nd.array(x.asnumpy() if isinstance(x, nd.NDArray) else x,
                            ctx=ctx) for x in inputs]
            outs.append(fn(*ins).asnumpy())
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)
    return outs[0]


def check_symbolic_forward(sym, inputs, expected, rtol=1e-4, atol=1e-6,
                           ctx=None, aux_states=None):
    """Bind ``sym`` with ``inputs`` and compare each output against
    ``expected`` (ref: test_utils.py:1061 check_symbolic_forward)."""
    args = {n: nd.array(v) if not isinstance(v, nd.NDArray) else v
            for n, v in zip(sym.list_arguments(), inputs)} \
        if not isinstance(inputs, dict) else inputs
    exe = sym.bind(ctx or current_context(), args=args,
                   aux_states=aux_states)
    outs = exe.forward()
    if len(outs) != len(expected):
        raise ValueError("check_symbolic_forward: %d outputs but %d "
                         "expected values — a truncated zip would pass "
                         "vacuously" % (len(outs), len(expected)))
    for got, want in zip(outs, expected):
        assert_almost_equal(got, want, rtol=rtol, atol=atol,
                            names=("forward", "expected"))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, inputs, out_grads, expected, rtol=1e-4,
                            atol=1e-6, ctx=None, aux_states=None):
    """Bind, run forward+backward with ``out_grads``, and compare each
    argument gradient (ref: test_utils.py:1129 check_symbolic_backward)."""
    ctx = ctx or current_context()
    names = sym.list_arguments()
    args = {n: nd.array(v) if not isinstance(v, nd.NDArray) else v
            for n, v in zip(names, inputs)} \
        if not isinstance(inputs, dict) else inputs
    grads = {n: nd.zeros(a.shape, ctx=ctx) for n, a in args.items()}
    exe = sym.bind(ctx, args=args, args_grad=grads,
                   aux_states=aux_states)
    exe.forward(is_train=True)
    exe.backward([g if isinstance(g, nd.NDArray) else nd.array(g)
                  for g in out_grads])
    if not isinstance(expected, dict):
        if len(expected) > len(names):
            raise ValueError(
                "check_symbolic_backward: %d expected gradients for %d "
                "arguments (shorter lists are partial checks; longer is "
                "always a miscount)" % (len(expected), len(names)))
        expected = dict(zip(names, expected))
    for n, want in expected.items():
        assert_almost_equal(grads[n], want, rtol=rtol, atol=atol,
                            names=("grad(%s)" % n, "expected"))
    return {n: g.asnumpy() for n, g in grads.items()}
