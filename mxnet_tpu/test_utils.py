"""Shared test utilities, shipped as library code like the reference's
``python/mxnet/test_utils.py`` (ref: test_utils.py:55 default_context,
:364 rand_ndarray, :512 assert_almost_equal, :883 check_numeric_gradient,
:1314 check_consistency).
"""
from __future__ import annotations

import numpy as _np

from .context import current_context, cpu
from . import ndarray as nd
from . import autograd

__all__ = ["default_context", "assert_almost_equal", "rand_ndarray",
           "rand_shape_nd", "check_numeric_gradient", "check_consistency",
           "almost_equal"]


def default_context():
    return current_context()


def rand_shape_nd(ndim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    arr = _np.random.uniform(-1, 1, size=shape).astype(dtype or _np.float32)
    out = nd.array(arr, ctx=ctx)
    if stype != "default":
        return out.tostype(stype)
    return out


def almost_equal(a, b, rtol=1e-5, atol=1e-8):
    return _np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else _np.asarray(b)
    if not _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        idx = _np.unravel_index(_np.argmax(_np.abs(a - b)), a.shape) \
            if a.shape else ()
        raise AssertionError(
            "arrays not almost equal (rtol=%g atol=%g): max |diff| %g at %s\n"
            "%s=%s\n%s=%s" % (rtol, atol, float(_np.max(_np.abs(a - b))), idx,
                              names[0], a, names[1], b))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Finite-difference gradient check of ``fn(*inputs) -> scalar NDArray``.
    ref: test_utils.py:883 check_numeric_gradient."""
    inputs = [x if isinstance(x, nd.NDArray) else nd.array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = fn(*inputs)
    y.backward()
    for x in inputs:
        xa = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(xa)
        flat = xa.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            yp = fn(*[nd.array(a.asnumpy()) if a is not x else
                      nd.array(xa.astype(_np.float32)) for a in inputs])
            fp = float(yp.asnumpy())
            flat[i] = orig - eps
            ym = fn(*[nd.array(a.asnumpy()) if a is not x else
                      nd.array(xa.astype(_np.float32)) for a in inputs])
            fm = float(ym.asnumpy())
            flat[i] = orig
            num.reshape(-1)[i] = (fp - fm) / (2 * eps)
        assert_almost_equal(x.grad.asnumpy(), num.astype(_np.float32),
                            rtol=rtol, atol=atol,
                            names=("autograd", "numeric"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-6):
    """Run ``fn`` under each context and compare outputs — the reference's
    cross-backend validator (ref: test_utils.py:1314)."""
    ctx_list = ctx_list or [cpu()]
    outs = []
    for ctx in ctx_list:
        with ctx:
            ins = [nd.array(x.asnumpy() if isinstance(x, nd.NDArray) else x,
                            ctx=ctx) for x in inputs]
            outs.append(fn(*ins).asnumpy())
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)
    return outs[0]
