"""Optimizers.

TPU-native re-design of the reference optimizer package
(ref: python/mxnet/optimizer/optimizer.py — base `Optimizer` :44, SGD :518,
Signum :934, FTML :1005, LARS :788, LBSGD :1061, DCASGD :1236, NAG :1285,
SGLD :1342, Adam :1412, AdaGrad :1520, AdaDelta :1635, RMSProp :1553,
Adamax :1688, Nadam :1742, Ftrl :1447-ish, `Updater` :1935).

Design differences (TPU-first):

- The reference dispatches to hand-fused CUDA kernels (`sgd_mom_update`,
  `adam_update`, ... in src/operator/optimizer_op.cc). Here every optimizer
  defines ONE pure function ``_step(weight, grad, states, lr, wd, ...)`` that
  is ``jax.jit``-compiled per (shape, dtype) — XLA fuses the whole update
  chain (rescale → clip → wd → momentum → write) into a single HBM pass,
  which is exactly what the hand-written kernels did.
- Hyperparameters that change per step (lr, wd, loss-scale) are traced
  scalars, so stepping the LR schedule never recompiles.
- ``multi_precision`` keeps an fp32 master weight next to bf16/fp16 weights
  (ref: optimizer.py:591 create_state_multi_precision) — on TPU the natural
  pairing is bf16 weights + fp32 master.
- Aggregated multi-weight updates (ref env `MXNET_OPTIMIZER_AGGREGATION_SIZE`)
  are unnecessary: ops on distinct weights are independently async-dispatched
  and XLA overlaps them; the knob is accepted for parity.
"""
from __future__ import annotations

import logging
import math
import pickle
import time as _time

import jax
import jax.numpy as jnp
import numpy as _np

from .. import profiler as _profiler
from ..base import canonical_dtype
from ..base import getenv as _getenv
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = [
    "Optimizer", "SGD", "Signum", "FTML", "LARS", "LBSGD", "DCASGD", "NAG",
    "SGLD", "Adam", "AdamW", "AdaGrad", "AdaDelta", "RMSProp", "Adamax",
    "Nadam", "Ftrl", "LAMB", "Test", "Updater", "create", "register",
    "get_updater",
]


def _as_data(x):
    return x._data if isinstance(x, NDArray) else x


def _is_low_precision(dtype):
    return _np.dtype(dtype) in (_np.dtype("float16"), _np.dtype(jnp.bfloat16))


class Optimizer:
    """Base optimizer (ref: python/mxnet/optimizer/optimizer.py:44)."""

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        # parity knob (ref: MXNET_OPTIMIZER_AGGREGATION_SIZE): accepted
        # and surfaced, but aggregation is a no-op here — independent
        # per-weight updates async-dispatch and XLA overlaps them, and
        # the packed path is MXTPU_FUSED_APPLY inside the fused step
        if aggregate_num is None:
            aggregate_num = int(
                _getenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", "0") or 0)
        self.aggregate_num = aggregate_num

        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._all_index_update_counts = {0: {}}
        self._index_update_count = self._all_index_update_counts[0]

        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        self.param_dict = param_dict if param_dict else {}

        self.lr_mult = {}
        self.wd_mult = {}
        self._jit_cache = {}

    # -- registry (ref: optimizer.py register/create_optimizer) ------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        """Return optimizer state for one weight (None | NDArray | tuple)."""
        return None

    def create_state_multi_precision(self, index, weight):
        """ref: optimizer.py:591 — fp32 master copy for low-precision
        weights."""
        if self.multi_precision and _is_low_precision(weight.dtype):
            master = NDArray(weight._data.astype(jnp.float32))
            return (master, self.create_state(index, master))
        if _is_low_precision(weight.dtype) and not self.multi_precision:
            logging.warning(
                "Accumulating with float16/bfloat16 in optimizer can lead to "
                "poor accuracy or slow convergence. Consider using "
                "multi_precision=True option of the optimizer")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_precision(weight.dtype):
            master, base_state = state
            grad32 = NDArray(grad._data.astype(jnp.float32))
            self.update(index, master, grad32, base_state)
            weight._data = master._data.astype(weight._data.dtype)
        else:
            self.update(index, weight, grad, state)

    # -- schedule / multipliers -------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """ref: optimizer.py set_lr_mult."""
        self.lr_mult = {}
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """ref: optimizer.py:381 — biases/beta get no wd, but _weight and
        _gamma keep it."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def set_current_context(self, device_id):
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    # -- pure step form (gluon fused train step) ---------------------------
    #
    # Next to the in-place ``update()`` every fused-capable optimizer
    # defines ``step_fn(weight, grad, state, lr, wd, rescale)``: a pure
    # function over jax arrays returning ``(new_weight, new_state)``,
    # mirroring update()'s jitted closure op-for-op so the fused train
    # step (gluon/fused_step.py) is bitwise-identical to the eager path.
    # lr/wd/rescale arrive as TRACED scalar operands (never baked
    # constants), so lr schedules and batch_size changes replay the same
    # compiled program; per-step host math that update() does in float64
    # (Adam's bias-corrected rate) lives in ``step_lr`` so both paths
    # round identically.

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        """Pure update: (new_weight, new_state) from jax-array operands.
        Optimizers that don't override this are not fused-step capable
        (the fused train step falls back to the eager path for them)."""
        raise NotImplementedError(
            "%s does not define the pure step_fn form; the gluon fused "
            "train step falls back to eager update()"
            % type(self).__name__)

    def step_fn_multi_precision(self, weight, grad, state, lr, wd, rescale):
        """Pure counterpart of ``update_multi_precision``: when this
        weight carries an fp32 master copy, step on the master and cast
        back, with the state shaped ``(master, base_state)`` exactly as
        ``create_state_multi_precision`` built it."""
        if self.multi_precision and _is_low_precision(weight.dtype):
            master, base = state
            new_master, new_base = self.step_fn(
                master, grad.astype(jnp.float32), base, lr, wd, rescale)
            return new_master.astype(weight.dtype), (new_master, new_base)
        return self.step_fn(weight, grad, state, lr, wd, rescale)

    def fused_step_supported(self):
        """Whether this optimizer defines the pure step_fn form."""
        return type(self).step_fn is not Optimizer.step_fn

    def fused_apply_supported(self):
        """Whether ``step_fn`` is purely ELEMENTWISE over (weight,
        grad, state leaves, lr, wd, rescale) — the property that makes
        the packed multi-tensor apply
        (pallas_kernels/optimizer_apply.py, ``MXTPU_FUSED_APPLY``)
        bitwise-equal to the per-parameter chain. Opt-in per optimizer:
        a reduction or shape-dependent term in the update math (e.g.
        LAMB's trust ratio) silently breaks under packing, so the base
        says no."""
        return False

    def step_lr(self, index):
        """Effective learning rate ``step_fn`` should receive for one
        weight this step — computed with the SAME host float64 math
        ``update()`` uses (call after ``_update_count``). Optimizers whose
        update bakes the step count into the rate (Adam) override this;
        the count itself never enters the trace, so stepping never
        retraces."""
        return self._get_lr(index)

    def _fused_static_key(self):
        """Hashable snapshot of the hyperparameters step_fn bakes as
        trace constants. Part of the fused-step cache key: mutating them
        (or load_states swapping in a differently-configured optimizer)
        must invalidate the compiled program instead of silently
        replaying stale constants."""
        return (type(self).__name__, self.clip_gradient,
                bool(self.multi_precision))

    # -- jit plumbing ------------------------------------------------------
    def _preprocess_grad(self, grad, rescale, clip):
        g = grad * rescale
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        return g

    def _jitted(self, key, fn):
        f = self._jit_cache.get(key)
        if f is None:
            # mxlint: disable=MX005 (per-optimizer keyed cache right here: _jitted IS this subsystem's bounded cache, keyed by update-rule signature)
            jf = jax.jit(fn)

            # one-shot first-call probe (the register._compile_probe
            # convention): trace + compile + first run lands in the
            # compile-attribution registry, then the probe unwraps
            # itself so steady-state hits pay nothing
            def probe(*args):
                t0 = _time.perf_counter()
                out = jf(*args)
                if self._jit_cache.get(key) is probe:
                    self._jit_cache[key] = jf
                _profiler.record_compile(
                    "optimizer:%s" % type(self).__name__,
                    key=repr(key)[:80],
                    dur_us=(_time.perf_counter() - t0) * 1e6)
                return out
            self._jit_cache[key] = probe
            f = probe
        return f

    def __getstate__(self):
        ret = self.__dict__.copy()
        ret["_jit_cache"] = {}
        return ret

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._jit_cache = {}


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class Test(Optimizer):
    """Trivial optimizer used by the reference's tests
    (ref: optimizer.py Test)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return NDArray(jnp.zeros_like(weight._data))

    def update(self, index, weight, grad, state):
        weight._data = weight._data + grad._data * self.rescale_grad
        state._data = weight._data


@register
class SGD(Optimizer):
    """SGD with momentum and optional lazy/multi-precision updates
    (ref: optimizer.py:518; fused kernels src/operator/optimizer_op.cc
    sgd_update/sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros_like(weight._data))

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        g = self._preprocess_grad(grad, rescale, self.clip_gradient)
        if self.momentum == 0.0:
            return weight - lr * (g + wd * weight), state
        m2 = self.momentum * state - lr * (g + wd * weight)
        return weight + m2, m2

    def fused_apply_supported(self):
        return True

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.momentum,)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient
        mom = self.momentum

        if mom == 0.0:
            def step(w, g, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                return w - lr * (g + wd * w)
            f = self._jitted(("sgd", weight.shape, str(weight.dtype)), step)
            weight._data = f(weight._data, grad._data, lr, wd,
                             self.rescale_grad)
        else:
            def step(w, g, m, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                m2 = mom * m - lr * (g + wd * w)
                return w + m2, m2
            f = self._jitted(("sgdm", weight.shape, str(weight.dtype)), step)
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad)


@register
class Signum(Optimizer):
    """Sign-of-gradient SGD (ref: optimizer.py:934, Bernstein et al. 2018)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros_like(weight._data))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip, mom, wd_lh = self.clip_gradient, self.momentum, self.wd_lh

        if mom == 0.0:
            def step(w, g, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                return (1 - lr * (wd + wd_lh)) * w - lr * jnp.sign(g)
            f = self._jitted(("signsgd", weight.shape, str(weight.dtype)),
                             step)
            weight._data = f(weight._data, grad._data, lr, wd,
                             self.rescale_grad)
        else:
            def step(w, g, m, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                m2 = mom * m - (1 - mom) * (g + wd * w)
                w2 = (1 - lr * wd_lh) * w + lr * jnp.sign(m2)
                return w2, m2
            f = self._jitted(("signum", weight.shape, str(weight.dtype)), step)
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad)


@register
class FTML(Optimizer):
    """Follow-the-moving-leader (ref: optimizer.py:1005)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = jnp.zeros_like(weight._data)
        return (NDArray(z), NDArray(z), NDArray(z))  # d, v, z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        b1, b2, eps, clip = self.beta1, self.beta2, self.epsilon, \
            self.clip_gradient
        d, v, z = state

        def step(w, g, d_, v_, z_, lr, wd, rescale, t):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            v2 = b2 * v_ + (1 - b2) * g * g
            d2 = (1 - b1 ** t) / lr * (jnp.sqrt(v2 / (1 - b2 ** t)) + eps)
            sigma = d2 - b1 * d_
            z2 = b1 * z_ + (1 - b1) * g - sigma * w
            w2 = -z2 / d2
            return w2, d2, v2, z2
        f = self._jitted(("ftml", weight.shape, str(weight.dtype)), step)
        weight._data, d._data, v._data, z._data = f(
            weight._data, grad._data, d._data, v._data, z._data, lr, wd,
            self.rescale_grad, t)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (ref: optimizer.py:788)."""

    def __init__(self, momentum=0.0, lars_eta=0.001, lars_epsilon=0,
                 momentum_correction=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lars_eta = lars_eta
        self.lars_epsilon = lars_epsilon
        self.momentum_correction = momentum_correction
        self.last_lr = None
        self.cur_lr = None

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros_like(weight._data))

    def _l2norm(self, v):
        return jnp.sqrt(jnp.sum((v * v).astype(jnp.float32)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        eta, eps, clip = self.lars_eta, self.lars_epsilon, self.clip_gradient
        mom = self.momentum
        if self.momentum_correction and self.last_lr is not None \
                and self.last_lr != 0:
            mom = mom * (lr / self.last_lr)
        self.last_lr, self.cur_lr = self.cur_lr if self.cur_lr is not None \
            else lr, lr

        name = self.idx2name.get(index, str(index))
        is_bias_or_gamma = name.endswith(("gamma", "beta", "bias"))

        def step(w, g, m, lr, wd, rescale, mom_):
            g = self._preprocess_grad(g, rescale, clip)
            if is_bias_or_gamma:
                ratio = 1.0
            else:
                w_norm = self._l2norm(w)
                g_norm = self._l2norm(g)
                ratio = jnp.where(
                    (w_norm > 0) & (g_norm > 0),
                    eta * w_norm / (g_norm + wd * w_norm + eps), 1.0)
            scaled_lr = lr * ratio
            upd = scaled_lr * (g + wd * w)
            if m is None:
                return w - upd, None
            m2 = mom_ * m + upd
            return w - m2, m2

        # momentum correction makes mom lr-dependent → traced arg, not key
        key = ("lars", weight.shape, str(weight.dtype), is_bias_or_gamma,
               state is None)
        f = self._jitted(key, step)
        if state is None:
            weight._data, _ = f(weight._data, grad._data, None, lr, wd,
                                self.rescale_grad, mom)
        else:
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad, mom)


@register
class LBSGD(Optimizer):
    """Large-batch SGD with warmup strategies (ref: optimizer.py:1061).
    Implements the 'lars' adaptive rate + linear/power warmup schedule."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros_like(weight._data))

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        strategy = self.warmup_strategy
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            mult = maxmult
        elif nwup <= 1:
            mult = 1.0
        else:
            if strategy == "linear":
                mult = 1.0 + (maxmult - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
            else:
                mult = 1.0
        return mult

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.warmup_strategy == "lars":
            w_norm = float(jnp.linalg.norm(weight._data.astype(jnp.float32)))
            g_norm = float(jnp.linalg.norm(
                (grad._data * self.rescale_grad).astype(jnp.float32)))
            if w_norm > 0 and g_norm > 0:
                self.lbmult = w_norm / (g_norm + wd * w_norm + 1e-9) * 0.001
            else:
                self.lbmult = 1.0
        else:
            self.lbmult = self._get_lbmult(self.num_update)
        lr = lr * self.lbmult
        clip, mom = self.clip_gradient, self.momentum

        if mom == 0.0:
            def step(w, g, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                return w - lr * (g + wd * w)
            f = self._jitted(("lbsgd", weight.shape, str(weight.dtype)), step)
            weight._data = f(weight._data, grad._data, lr, wd,
                             self.rescale_grad)
        else:
            def step(w, g, m, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                m2 = mom * m - lr * (g + wd * w)
                return w + m2, m2
            f = self._jitted(("lbsgdm", weight.shape, str(weight.dtype)), step)
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py:1236)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, NDArray(weight._data))
        return (NDArray(jnp.zeros_like(weight._data)), NDArray(weight._data))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, lamda, clip = self.momentum, self.lamda, self.clip_gradient
        m, prev = state

        def step(w, g, m_, prev_, lr, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip)
            comp = g + wd * w + lamda * g * g * (w - prev_)
            if m_ is None:
                m2 = -lr * comp
            else:
                m2 = mom * m_ - lr * comp
            return w + m2, m2, w
        f = self._jitted(("dcasgd", weight.shape, str(weight.dtype),
                          m is None), step)
        if m is None:
            weight._data, _, prev._data = f(
                weight._data, grad._data, None, prev._data, lr, wd,
                self.rescale_grad)
        else:
            weight._data, m._data, prev._data = f(
                weight._data, grad._data, m._data, prev._data, lr, wd,
                self.rescale_grad)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py:1342)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def update(self, index, weight, grad, state):
        from .. import random as _random
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient
        key = _random.next_key()

        def step(w, g, lr, wd, rescale, key):
            g = self._preprocess_grad(g, rescale, clip)
            noise = jax.random.normal(key, w.shape, w.dtype) * \
                jnp.sqrt(lr).astype(w.dtype)
            return w - lr / 2 * (g + wd * w) + noise
        f = self._jitted(("sgld", weight.shape, str(weight.dtype)), step)
        weight._data = f(weight._data, grad._data, lr, wd, self.rescale_grad,
                         key)


@register
class Adam(Optimizer):
    """Adam (ref: optimizer.py:1412; fused kernel adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        # lr is the bias-corrected rate from step_lr (the lr_t update()
        # computes host-side) so the step count never enters the trace
        m, v = state
        g = self._preprocess_grad(grad, rescale, self.clip_gradient) \
            + wd * weight
        m2 = self.beta1 * m + (1 - self.beta1) * g
        v2 = self.beta2 * v + (1 - self.beta2) * g * g
        w2 = weight - lr * m2 / (jnp.sqrt(v2) + self.epsilon)
        return w2, (m2, v2)

    def fused_apply_supported(self):
        return True

    def step_lr(self, index):
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        return self._get_lr(index) * math.sqrt(coef2) / coef1

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.beta1, self.beta2,
                                              self.epsilon)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        b1, b2, eps, clip = self.beta1, self.beta2, self.epsilon, \
            self.clip_gradient
        m, v = state

        def step(w, g, m_, v_, lr_t, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            m2 = b1 * m_ + (1 - b1) * g
            v2 = b2 * v_ + (1 - b2) * g * g
            w2 = w - lr_t * m2 / (jnp.sqrt(v2) + eps)
            return w2, m2, v2
        f = self._jitted(("adam", weight.shape, str(weight.dtype)), step)
        weight._data, m._data, v._data = f(weight._data, grad._data, m._data,
                                           v._data, lr_t, wd,
                                           self.rescale_grad)


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay
    (ref: src/operator/contrib/adamw.cc, python contrib.optimizer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        b1, b2, eps, clip = self.beta1, self.beta2, self.epsilon, \
            self.clip_gradient
        m, v = state

        def step(w, g, m_, v_, lr_t, lr, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip)
            m2 = b1 * m_ + (1 - b1) * g
            v2 = b2 * v_ + (1 - b2) * g * g
            w2 = w - lr_t * m2 / (jnp.sqrt(v2) + eps) - lr * wd * w
            return w2, m2, v2
        f = self._jitted(("adamw", weight.shape, str(weight.dtype)), step)
        weight._data, m._data, v._data = f(weight._data, grad._data, m._data,
                                           v._data, lr_t, lr, wd,
                                           self.rescale_grad)


@register
class AdaGrad(Optimizer):
    """AdaGrad (ref: optimizer.py:1520)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros_like(weight._data))

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        g = self._preprocess_grad(grad, rescale, self.clip_gradient) \
            + wd * weight
        h2 = state + g * g
        return weight - lr * g / (jnp.sqrt(h2) + self.float_stable_eps), h2

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.float_stable_eps,)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        eps, clip = self.float_stable_eps, self.clip_gradient

        def step(w, g, h, lr, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            h2 = h + g * g
            return w - lr * g / (jnp.sqrt(h2) + eps), h2
        f = self._jitted(("adagrad", weight.shape, str(weight.dtype)), step)
        weight._data, state._data = f(weight._data, grad._data, state._data,
                                      lr, wd, self.rescale_grad)


@register
class AdaDelta(Optimizer):
    """AdaDelta (ref: optimizer.py:1635)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        rho, eps, clip = self.rho, self.epsilon, self.clip_gradient
        acc_g, acc_delta = state

        def step(w, g, ag, ad, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            ag2 = rho * ag + (1 - rho) * g * g
            delta = jnp.sqrt(ad + eps) / jnp.sqrt(ag2 + eps) * g
            ad2 = rho * ad + (1 - rho) * delta * delta
            return w - delta, ag2, ad2
        f = self._jitted(("adadelta", weight.shape, str(weight.dtype)), step)
        weight._data, acc_g._data, acc_delta._data = f(
            weight._data, grad._data, acc_g._data, acc_delta._data, wd,
            self.rescale_grad)


@register
class RMSProp(Optimizer):
    """RMSProp, non-centered (Hinton) and centered (Graves 2013) variants
    (ref: optimizer.py:1553; kernels rmsprop_update/rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (NDArray(jnp.zeros_like(weight._data)),
                    NDArray(jnp.zeros_like(weight._data)),
                    NDArray(jnp.zeros_like(weight._data)))  # n, g, delta
        return NDArray(jnp.zeros_like(weight._data))

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        g1, g2, eps = self.gamma1, self.gamma2, self.epsilon
        clip_w = self.clip_weights
        g = self._preprocess_grad(grad, rescale, self.clip_gradient) \
            + wd * weight
        if not self.centered:
            n2 = (1 - g1) * g * g + g1 * state
            w2 = weight - lr * g / jnp.sqrt(n2 + eps)
            if clip_w is not None:
                w2 = jnp.clip(w2, -clip_w, clip_w)
            return w2, n2
        n, gbar, delta = state
        n2 = (1 - g1) * g * g + g1 * n
        gb2 = (1 - g1) * g + g1 * gbar
        d2 = g2 * delta - lr * g / jnp.sqrt(n2 - gb2 * gb2 + eps)
        w2 = weight + d2
        if clip_w is not None:
            w2 = jnp.clip(w2, -clip_w, clip_w)
        return w2, (n2, gb2, d2)

    def _fused_static_key(self):
        return super()._fused_static_key() + (
            self.gamma1, self.gamma2, self.epsilon, self.centered,
            self.clip_weights)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g1, g2, eps = self.gamma1, self.gamma2, self.epsilon
        clip, clip_w = self.clip_gradient, self.clip_weights

        if not self.centered:
            def step(w, g, n, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip) + wd * w
                n2 = (1 - g1) * g * g + g1 * n
                w2 = w - lr * g / jnp.sqrt(n2 + eps)
                if clip_w is not None:
                    w2 = jnp.clip(w2, -clip_w, clip_w)
                return w2, n2
            f = self._jitted(("rmsprop", weight.shape, str(weight.dtype)),
                             step)
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad)
        else:
            n, gbar, delta = state

            def step(w, g, n_, gb, d, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip) + wd * w
                n2 = (1 - g1) * g * g + g1 * n_
                gb2 = (1 - g1) * g + g1 * gb
                d2 = g2 * d - lr * g / jnp.sqrt(n2 - gb2 * gb2 + eps)
                w2 = w + d2
                if clip_w is not None:
                    w2 = jnp.clip(w2, -clip_w, clip_w)
                return w2, n2, gb2, d2
            f = self._jitted(("rmspropalex", weight.shape, str(weight.dtype)),
                             step)
            weight._data, n._data, gbar._data, delta._data = f(
                weight._data, grad._data, n._data, gbar._data, delta._data,
                lr, wd, self.rescale_grad)


@register
class Adamax(Optimizer):
    """AdaMax — infinity-norm Adam variant (ref: optimizer.py:1688)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr_t = lr / (1. - self.beta1 ** t)
        b1, b2, clip = self.beta1, self.beta2, self.clip_gradient
        m, u = state

        def step(w, g, m_, u_, lr_t, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            m2 = b1 * m_ + (1 - b1) * g
            u2 = jnp.maximum(b2 * u_, jnp.abs(g))
            return w - lr_t * m2 / (u2 + 1e-8), m2, u2
        f = self._jitted(("adamax", weight.shape, str(weight.dtype)), step)
        weight._data, m._data, u._data = f(weight._data, grad._data, m._data,
                                           u._data, lr_t, wd,
                                           self.rescale_grad)


@register
class Nadam(Optimizer):
    """Nesterov Adam (ref: optimizer.py:1742)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        b1, b2, eps, clip = self.beta1, self.beta2, self.epsilon, \
            self.clip_gradient
        momentum_t = b1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = b1 * (1. - 0.5 * 0.96 **
                             ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state

        # t-dependent scalars enter as traced args so stepping never
        # recompiles (cache key is shape/dtype only)
        def step(w, g, m_, v_, lr, wd, rescale, m_sched, m_sched_next,
                 mom_t, mom_t_1, v_corr):
            g = self._preprocess_grad(g, rescale, clip) + wd * w
            g_prime = g / (1. - m_sched)
            m2 = b1 * m_ + (1. - b1) * g
            m2_prime = m2 / (1. - m_sched_next)
            v2 = b2 * v_ + (1. - b2) * g * g
            v2_prime = v2 / v_corr
            m_bar = (1. - mom_t) * g_prime + mom_t_1 * m2_prime
            return w - lr * m_bar / (jnp.sqrt(v2_prime) + eps), m2, v2
        f = self._jitted(("nadam", weight.shape, str(weight.dtype)), step)
        weight._data, m._data, v._data = f(
            weight._data, grad._data, m._data, v._data, lr, wd,
            self.rescale_grad, self.m_schedule, m_schedule_next, momentum_t,
            momentum_t_1, 1. - b2 ** t)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (ref: optimizer.py Ftrl; kernel ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),   # z
                NDArray(jnp.zeros_like(weight._data)))   # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        l1, beta, clip = self.lamda1, self.beta, self.clip_gradient
        z, n = state

        def step(w, g, z_, n_, lr, wd, rescale):
            g = self._preprocess_grad(g, rescale, clip)
            sigma = (jnp.sqrt(n_ + g * g) - jnp.sqrt(n_)) / lr
            z2 = z_ + g - sigma * w
            n2 = n_ + g * g
            w2 = jnp.where(
                jnp.abs(z2) > l1,
                (jnp.sign(z2) * l1 - z2) /
                ((beta + jnp.sqrt(n2)) / lr + wd), 0.0).astype(w.dtype)
            return w2, z2, n2
        f = self._jitted(("ftrl", weight.shape, str(weight.dtype)), step)
        weight._data, z._data, n._data = f(weight._data, grad._data, z._data,
                                           n._data, lr, wd, self.rescale_grad)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref: optimizer.py:1285)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros_like(weight._data))

    def step_fn(self, weight, grad, state, lr, wd, rescale):
        mom = self.momentum
        if state is None:
            g = self._preprocess_grad(grad, rescale, self.clip_gradient)
            return weight - lr * (g + wd * weight), None
        g = self._preprocess_grad(grad, rescale, self.clip_gradient) \
            + wd * weight
        m2 = mom * state + g
        return weight - lr * (g + mom * m2), m2

    def _fused_static_key(self):
        return super()._fused_static_key() + (self.momentum,)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, clip = self.momentum, self.clip_gradient

        if state is None:
            def step(w, g, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip)
                return w - lr * (g + wd * w)
            f = self._jitted(("nag0", weight.shape, str(weight.dtype)), step)
            weight._data = f(weight._data, grad._data, lr, wd,
                             self.rescale_grad)
        else:
            def step(w, g, m, lr, wd, rescale):
                g = self._preprocess_grad(g, rescale, clip) + wd * w
                m2 = mom * m + g
                return w - lr * (g + mom * m2), m2
            f = self._jitted(("nag", weight.shape, str(weight.dtype)), step)
            weight._data, state._data = f(weight._data, grad._data,
                                          state._data, lr, wd,
                                          self.rescale_grad)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for batch training (LAMB), as added to the
    reference in 1.6 (ref: src/operator/optimizer_op.cc lamb_update_phase1/2)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros_like(weight._data)),
                NDArray(jnp.zeros_like(weight._data)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        b1, b2, eps, clip = self.beta1, self.beta2, self.epsilon, \
            self.clip_gradient
        lo, hi, bias_corr = self.lower_bound, self.upper_bound, \
            self.bias_correction
        m, v = state

        def step(w, g, m_, v_, lr, wd, rescale, coef1, coef2):
            g = self._preprocess_grad(g, rescale, clip)
            m2 = b1 * m_ + (1 - b1) * g
            v2 = b2 * v_ + (1 - b2) * g * g
            if bias_corr:
                mhat = m2 / coef1
                vhat = v2 / coef2
            else:
                mhat, vhat = m2, v2
            r = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            w_norm = jnp.linalg.norm(w.astype(jnp.float32))
            r_norm = jnp.linalg.norm(r.astype(jnp.float32))
            if lo is not None:
                w_norm = jnp.maximum(w_norm, lo)
            if hi is not None:
                w_norm = jnp.minimum(w_norm, hi)
            ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm,
                              1.0).astype(w.dtype)
            return w - lr * ratio * r, m2, v2
        f = self._jitted(("lamb", weight.shape, str(weight.dtype)), step)
        weight._data, m._data, v._data = f(
            weight._data, grad._data, m._data, v._data, lr, wd,
            self.rescale_grad, 1 - b1 ** t, 1 - b2 ** t)


# backward-compat alias (ref: optimizer.py ccSGD deprecated alias)
ccSGD = SGD


class Updater:
    """Applies an optimizer to indexed weights, owning per-index state
    (ref: optimizer.py:1935 Updater, get_updater :2035; this is what kvstore
    set_optimizer installs server-side)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def ensure_state(self, index, weight):
        """Create-or-resync the optimizer state for one index (the lazy
        init block of ``__call__``, shared with the gluon fused train
        step so both paths own the SAME state store — save_states /
        load_states round-trip across them)."""
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
            # allocation-ledger choke point (ISSUE 13a): optimizer
            # state is long-lived HBM — tag its leaves at creation
            from .. import storage as _storage
            _storage.ledger_register_tree(self.states[index], "opt_state",
                                          site="opt_state[%s]" % (index,))
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index],
                                                         weight.context)
            self.states_synced[index] = True
        return self.states[index]

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices = [index]
            grads = [grad]
            weights = [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            self.ensure_state(i, w)
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(self.sync_state_context(i, context)
                               for i in state)
        return state

    def set_states(self, states):
        """ref: optimizer.py Updater.set_states — accepts (states, optimizer)
        pickles for checkpoint resume."""
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        # stored states are numpy trees; rehydrate lazily
        self.states = {k: _rehydrate(v) for k, v in self.states.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        dehydrated = {k: _dehydrate(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((dehydrated, self.optimizer))
        return pickle.dumps(dehydrated)


def _dehydrate(state):
    if isinstance(state, NDArray):
        return state.asnumpy()
    if isinstance(state, (tuple, list)):
        return type(state)(_dehydrate(s) for s in state)
    return state


def _rehydrate(state):
    if isinstance(state, _np.ndarray):
        return nd.array(state, dtype=canonical_dtype(state.dtype))
    if isinstance(state, (tuple, list)):
        return type(state)(_rehydrate(s) for s in state)
    return state


def get_updater(optimizer):
    """ref: optimizer.py:2035."""
    return Updater(optimizer)
