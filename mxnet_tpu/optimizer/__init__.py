"""``mxnet_tpu.optimizer`` — weight-update rules.

ref: python/mxnet/optimizer/__init__.py.
"""
from .optimizer import *  # noqa: F401,F403
from .optimizer import Optimizer, Updater, create, register, get_updater

opt_registry = Optimizer.opt_registry
