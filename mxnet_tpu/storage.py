"""Device memory / storage introspection + the tagged allocation ledger.

TPU-native re-design of the reference storage layer (ref: src/storage/,
include/mxnet/storage.h:36-137). The reference implements its own pooled
allocators (GPUPooledStorageManager, pooled_storage_manager.h:52) because
cudaMalloc is slow; on TPU the PJRT runtime owns the HBM allocator (BFC-style
pooling lives below XLA), so the framework's job is *introspection and
control*, not reimplementation:

* per-device usage stats (≙ the pool counters the reference keeps) — with a
  ``jax.live_arrays()`` fallback for backends (CPU) whose devices report no
  ``memory_stats()``, so the numbers exist on the tier-1 suite too,
* an explicit release hook (≙ ``Storage::ReleaseAll`` / ``MXStorageEmptyCache``)
  implemented by dropping framework references and forcing a GC,
* host-side pinned/shared-memory roles are covered by the data-IO stack
  (gluon DataLoader shared workers).

Tagged allocation ledger (ISSUE 13 tentpole a) — the attribution the
reference gets from ``Storage::Get()->Alloc/Free`` pooled-allocator
accounting and we lost in the JAX graft. Every device buffer created at a
framework choke point (``register.invoke`` results, bulk-segment delivery,
``Parameter._adopt_fused``, optimizer-state creation, creation factories,
io device placement, kvstore pull buffers, pallas autotune workspaces) is
weakref-registered with a category tag:

    param / grad / opt_state / activation / io / workspace / other

Hot-path price engineering (the flightrec discipline): the per-op dispatch
site appends ONE ``(weakref, site)`` pair to a per-tag ``deque`` — no
callback closure, no nbytes read (``jax.Array.nbytes`` costs ~3us), no
lock; ``deque.append`` is a GIL-atomic C call. All bookkeeping (folding
pending appends into the live-entry table, pruning dead/donated buffers,
computing per-tag byte totals) happens at DRAIN time on whoever asks —
the profiler memory sampler, the memwatch daemon, ``metrics()`` — under
one named lock. A buffer leaves the ledger exactly once: its weakref dies
(refcount/GC) or XLA donation marks it ``is_deleted()`` (``donate_argnums``,
``OpDef.inplace``), both observed by the same prune. Call-site attribution
is sampled (1-in-``_SITE_SAMPLE`` helper registrations walk the stack) so
a leak dump can name allocation sites without pricing every allocation.

``BENCH_MODEL=memory_overhead`` gates the add/retire pair at <0.5% of
eager dispatch. ``MXTPU_MEMLEDGER=0`` is the kill switch; the hot sites
additionally sit behind the shared ``_HOOKS and _LIVE`` telemetry guard,
so with everything off the ledger costs nothing at all.
"""
from __future__ import annotations

import collections
import gc
import weakref

from ._debug import locktrace as _locktrace
from .base import getenv as _getenv

__all__ = ["DeviceStats", "stats", "total_bytes_in_use", "release_all",
           "empty_cache", "reset_peak",
           "LEDGER_TAGS", "ledger_register", "ledger_register_tree",
           "ledger_retire", "ledger_metrics", "ledger_reset",
           "pending_append", "set_ledger_enabled", "memory_metrics",
           "note_modeled_peak", "headroom", "bump", "counters"]

# Framework-side high-water mark per device, updated on every stats() call.
# PJRT's own peak_bytes_in_use is cumulative for the process and cannot be
# reset, so per-step peak deltas (profiler memory samples between steps)
# come from this re-derivable mark instead: reset_peak() rebases it to the
# current usage and the next samples grow it from there.
_hwm_lock = _locktrace.named_lock("storage.hwm")
_hwm = {}  # str(device) -> high-water bytes_in_use since last reset_peak()
# newest stats() snapshot: str(device) -> (bytes_in_use, peak_since_reset,
# bytes_limit). The headroom gauge reads this instead of re-walking the
# backend per training step.
_last_stats = {}  # mxlint: disable=MX003 (written only under _hwm_lock in stats(); readers take a GIL-atomic snapshot)


class DeviceStats:
    """Memory stats for one device (≙ the pool counters in
    src/storage/pooled_storage_manager.h:61-115)."""

    def __init__(self, device, raw):
        self.device = device
        self.bytes_in_use = int(raw.get("bytes_in_use", 0))
        self.peak_bytes_in_use = int(raw.get("peak_bytes_in_use", 0))
        self.bytes_limit = int(raw.get("bytes_limit", 0))
        self.num_allocs = int(raw.get("num_allocs", 0))
        self.largest_alloc_size = int(raw.get("largest_alloc_size", 0))
        self.peak_since_reset = 0  # filled in by stats()
        self.raw = dict(raw)

    def __repr__(self):
        return ("DeviceStats(%s, in_use=%d, peak=%d, limit=%d)"
                % (self.device, self.bytes_in_use, self.peak_bytes_in_use,
                   self.bytes_limit))


def _live_array_stats():
    """{str(device): {bytes_in_use, num_allocs, largest_alloc_size}}
    synthesized from ``jax.live_arrays()`` — the introspection fallback
    for backends whose devices report no ``memory_stats()`` (CPU). A
    sharded array's bytes split evenly across its devices. O(live
    arrays); callers are the 10Hz sampler / 1Hz memwatch poll, never a
    hot path."""
    import jax
    per = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return per
    for a in arrays:
        try:
            if a.is_deleted():
                continue
            nb = int(a.nbytes)
            devs = list(a.devices())
        except Exception:
            continue
        if not devs:
            continue
        share = nb // len(devs)
        for d in devs:
            st = per.setdefault(str(d), {"bytes_in_use": 0,
                                         "num_allocs": 0,
                                         "largest_alloc_size": 0})
            st["bytes_in_use"] += share
            st["num_allocs"] += 1
            if share > st["largest_alloc_size"]:
                st["largest_alloc_size"] = share
    return per


def stats():
    """Per-device memory stats from PJRT. Devices that report no stats
    (CPU) synthesize ``bytes_in_use`` from ``jax.live_arrays()`` so the
    numbers exist on the tier-1 suite. Each call advances the
    framework-side high-water mark backing ``peak_since_reset`` (see
    ``reset_peak``)."""
    import jax
    out = []
    synth = None  # computed lazily, once, only if some device needs it
    with _hwm_lock:
        for d in jax.devices():
            try:
                raw = d.memory_stats() or {}
            except Exception:
                raw = {}
            if not raw:
                if synth is None:
                    synth = _live_array_stats()
                raw = dict(synth.get(str(d), ()))
                if raw:
                    raw["source"] = "live_arrays"
            ds = DeviceStats(d, raw)
            key = str(d)
            mark = _hwm.get(key)
            if mark is None or ds.bytes_in_use > mark:
                mark = ds.bytes_in_use
                _hwm[key] = mark
            ds.peak_since_reset = mark
            _last_stats[key] = (ds.bytes_in_use, mark, ds.bytes_limit)
            out.append(ds)
    return out


def reset_peak():
    """Rebase the framework-side peak mark to current usage (per device),
    so ``DeviceStats.peak_since_reset`` measures the high-water mark of the
    window since this call — e.g. one training step between two profiler
    memory samples. PJRT's own ``peak_bytes_in_use`` is process-cumulative
    and stays untouched. Returns {str(device): rebased bytes_in_use}."""
    import jax
    out = {}
    synth = None
    with _hwm_lock:
        for d in jax.devices():
            try:
                raw = d.memory_stats() or {}
            except Exception:
                raw = {}
            if not raw:
                if synth is None:
                    synth = _live_array_stats()
                raw = synth.get(str(d), {})
            key = str(d)
            _hwm[key] = int(raw.get("bytes_in_use", 0))
            out[key] = _hwm[key]
    return out


def total_bytes_in_use():
    return sum(s.bytes_in_use for s in stats())


def release_all():
    """Drop unreferenced device buffers (ref: Storage::ReleaseAll,
    include/mxnet/storage.h; MXStorageEmptyCache in the C API). PJRT frees a
    buffer when its last reference dies, so this forces a collection pass and
    deletes donated/aliased temporaries. Counted in
    ``metrics()['memory']['empty_cache_calls']`` (the account contract:
    counts with profiling off)."""
    bump("empty_cache_calls")
    gc.collect()


empty_cache = release_all


# ---------------------------------------------------------------------------
# Allocation accounting counters (ISSUE 13 satellite: metrics()['memory']
# is the single owner — storage.alloc_fallbacks moved here from the
# generic profiler counter namespace).
# ---------------------------------------------------------------------------

# mxlint: disable=MX003 (GIL-atomic best-effort counters on degradation paths, same contract as ndarray/register._STATS)
_counters = {
    "alloc_fallbacks": 0,   # device placement degraded to a host array
    "empty_cache_calls": 0,
}


def bump(name, delta=1):
    """Accumulate one allocation-accounting counter. Unconditional (the
    ``profiler.account`` contract): degradation accounting must be
    trustworthy with profiling off."""
    _counters[name] = _counters.get(name, 0) + delta


def counters():
    return dict(_counters)


# ---------------------------------------------------------------------------
# The tagged allocation ledger (ISSUE 13 tentpole a).
# ---------------------------------------------------------------------------

LEDGER_TAGS = ("param", "grad", "opt_state", "activation", "io",
               "workspace", "checkpoint", "other")

_LEDGER_ON = _getenv("MXTPU_MEMLEDGER", "1") not in ("0", "false", "off")
# emergency bound per pending deque: maxlen drops OLDEST registrations if
# no drainer runs for a long time (daemons dead) — bounded memory beats
# perfect accounting in that degenerate state. At full eager rate
# (~30k ops/s) this is several seconds of slack against the 1s memwatch
# poll and the 0.1s profiler sampler.
_PENDING_CAP = 1 << 16
# STABLE deque objects: hot modules cache `pending_append(tag)` bound
# methods at import, so reset clears these in place, never replaces them.
_pending = {t: collections.deque(maxlen=_PENDING_CAP) for t in LEDGER_TAGS}

_ledger_lock = _locktrace.named_lock("storage.ledger")
_entries = {}       # id(buf) -> [weakref, tag, nbytes | None, site | None]
# Explicit retires that arrived before their registration drained:
# id(buf) -> weakref(buf). The weakref validates the marker at drain
# time — CPython reuses freed addresses, and a stale id-only marker
# would silently swallow some FUTURE buffer's registration forever.
_retired = {}
_cum = {t: 0 for t in LEDGER_TAGS}   # registrations integrated, per tag
_modeled_peaks = {}  # program name -> modeled peak bytes (fused_step AOT)
# sampled call-site capture budget: 1-in-N helper registrations walk the
# stack (a full walk costs ~10us; the sample keeps attribution ~free)
_SITE_SAMPLE = 64
_site_tick = [0]  # mxlint: disable=MX003 (GIL-atomic bump; a lost update skews the sample phase, never the accounting)
_watch_started = [False]  # mxlint: disable=MX003 (GIL-atomic once-flag; ensure_thread is idempotent so a racing double start is harmless)


def set_ledger_enabled(enabled):
    """Runtime kill switch (``MXTPU_MEMLEDGER`` sets the process
    default). Returns the previous value."""
    global _LEDGER_ON
    prev = _LEDGER_ON
    _LEDGER_ON = bool(enabled)
    return prev


def pending_append(tag):
    """The raw hot-path registration primitive: the bound
    ``deque.append`` for ``tag``'s pending queue. Hot modules cache it at
    import and append ``(weakref.ref(buf), site)`` pairs directly —
    everything else (liveness, sizes, totals) is drain-time work. The
    deque object is stable for the life of the process."""
    return _pending[tag].append


# Memoized profiler module ref: the lazy import breaks the storage <->
# profiler cycle (profiler pulls storage only inside sample_memory),
# and reading `_PROFILER._LIVE` inline in ledger_register spares the
# helper-call cost the <0.5%-of-step budget cannot afford.
_PROFILER = None


def _capture_site():
    """First stack frame outside this module / the ndarray package —
    the user-ish code that triggered the allocation."""
    import sys
    try:
        f = sys._getframe(2)
    except ValueError:
        return None
    for _ in range(12):
        if f is None:
            return None
        fn = f.f_code.co_filename
        if "mxnet_tpu" not in fn.replace("\\", "/"):
            return "%s:%d" % (fn.rsplit("/", 1)[-1], f.f_lineno)
        f = f.f_back
    return None


def ledger_register(buf, tag, site=None):
    """Register one device buffer (a ``jax.Array`` or an NDArray, whose
    buffer is taken) under ``tag``. Cheap no-op when the ledger is off or
    telemetry is fully disabled (the shared ``_LIVE`` guard). ``site``
    labels the allocation for the leak watchdog's top-sites table; when
    omitted, a sampled stack capture fills it in 1-in-``_SITE_SAMPLE``
    calls."""
    p = _PROFILER
    if p is None:
        from . import profiler as p
        globals()["_PROFILER"] = p
    if not (_LEDGER_ON and p._LIVE):
        return
    if not _watch_started[0]:
        # the first registration lazily starts the memwatch daemon (the
        # step-watchdog idiom): leak detection is on whenever the
        # ledger has anything to watch, no wiring required
        _watch_started[0] = True
        try:
            from ._debug import memwatch
            memwatch.ensure_thread()
        except Exception:
            pass
    b = getattr(buf, "_buf", buf)
    if site is None:
        _site_tick[0] += 1
        if _site_tick[0] % _SITE_SAMPLE == 0:
            site = _capture_site()
    try:
        _pending[tag].append((weakref.ref(b), site))
    except TypeError:
        pass  # not weakref-able (python scalar, numpy view): not a
        #      device buffer the ledger needs to own


def ledger_register_tree(tree, tag, site=None):
    """Register every NDArray/array leaf of a nested tuple/list state
    tree (the optimizer-state shape)."""
    if tree is None:
        return
    if isinstance(tree, (tuple, list)):
        for t in tree:
            ledger_register_tree(t, tag, site)
        return
    if hasattr(tree, "_buf") or hasattr(tree, "nbytes"):
        ledger_register(tree, tag, site)


def ledger_retire(buf):
    """Explicitly retire a buffer (donation sites that want deterministic
    accounting before GC gets there). Exactly-once: the entry pop is the
    single ownership transfer; the weakref death or ``is_deleted()``
    prune later finds nothing."""
    b = getattr(buf, "_buf", buf)
    key = id(b)
    with _ledger_lock:
        if _entries.pop(key, None) is None:
            try:
                _retired[key] = weakref.ref(b)
            except TypeError:
                return
            if len(_retired) > 4 * _PENDING_CAP:
                _retired.clear()  # unmatched retires must not leak


# Drain precedence: generic tags fold in first so a buffer re-registered
# under a more SPECIFIC tag in the same pending window keeps the
# specific one (nd.array creates a weight as 'other', Parameter adoption
# re-registers it as 'param' — param must win the id(buf) table slot).
_DRAIN_ORDER = ("activation", "io", "workspace", "other", "grad",
                "opt_state", "param")


def _drain_locked():
    """Fold pending registrations into the live-entry table. Caller
    holds _ledger_lock. Entries whose buffer already died (the typical
    eager temporary) integrate as nothing — that IS their retirement."""
    import jax
    tracer = jax.core.Tracer
    for tag in _DRAIN_ORDER:
        pop = _pending[tag].popleft  # bound-method hoist: the drain is
        #                              priced per entry by the bench gate
        while True:
            try:
                ref, site = pop()
            except IndexError:
                break
            o = ref()
            if o is None or isinstance(o, tracer):
                continue  # died before integration / trace-time phantom
            deleted = getattr(o, "is_deleted", None)
            if deleted is not None:
                try:
                    if deleted():
                        continue  # donated away before integration
                except Exception:
                    continue
            key = id(o)
            marker = _retired.get(key)
            if marker is not None:
                # mxlint: disable=MX003 (caller holds _ledger_lock — the function's contract, see docstring)
                del _retired[key]
                if marker() is o:
                    continue  # the retire matches THIS buffer
                # stale marker (its buffer died, the id was reused):
                # fall through and register the new buffer normally
            # mxlint: disable=MX003 (caller holds _ledger_lock — the function's contract, see docstring)
            _entries[key] = [ref, tag, None, site]
            _cum[tag] = _cum.get(tag, 0) + 1
    # markers whose buffer died can never legitimately match again —
    # any future hit on that id is address reuse. Prune them.
    for k in [k for k, r in _retired.items() if r() is None]:
        # mxlint: disable=MX003 (caller holds _ledger_lock — the function's contract, see docstring)
        del _retired[k]


def _walk_locked():
    """(live bytes by tag, live counts by tag, live bytes by (tag, site))
    — prunes dead/donated entries as it goes. Caller holds _ledger_lock."""
    by_tag = dict.fromkeys(LEDGER_TAGS, 0)
    counts = dict.fromkeys(LEDGER_TAGS, 0)
    sites = {}
    dead = []
    for key, ent in _entries.items():
        o = ent[0]()
        if o is None:
            dead.append(key)
            continue
        deleted = getattr(o, "is_deleted", None)
        if deleted is not None:
            try:
                if deleted():
                    dead.append(key)  # donation retired it on-device
                    continue
            except Exception:
                dead.append(key)
                continue
        nb = ent[2]
        if nb is None:
            try:
                nb = int(o.nbytes)
            except Exception:
                nb = 0
            ent[2] = nb
        tag = ent[1]
        by_tag[tag] = by_tag.get(tag, 0) + nb
        counts[tag] = counts.get(tag, 0) + 1
        if ent[3]:
            k = (tag, ent[3])
            sites[k] = sites.get(k, 0) + nb
    for key in dead:
        # mxlint: disable=MX003 (caller holds _ledger_lock — the function's contract, see docstring)
        del _entries[key]
    return by_tag, counts, sites


def ledger_metrics(top_sites=8):
    """One drained snapshot of the ledger: live bytes/counts by tag,
    total, cumulative integrations, and the top-``top_sites`` allocation
    sites by live bytes."""
    with _ledger_lock:
        _drain_locked()
        by_tag, counts, sites = _walk_locked()
        cum = dict(_cum)
    top = sorted(sites.items(), key=lambda kv: -kv[1])[:top_sites]
    return {
        "enabled": bool(_LEDGER_ON),
        "by_tag": by_tag,
        "counts": counts,
        "total_bytes": sum(by_tag.values()),
        "registered_total": cum,
        "top_sites": [{"tag": t, "site": s, "bytes": b}
                      for (t, s), b in top],
    }


def ledger_reset():
    """Drop every ledger entry and pending registration (test
    isolation)."""
    with _ledger_lock:
        for dq in _pending.values():
            dq.clear()
        _entries.clear()
        _retired.clear()
        for t in list(_cum):
            _cum[t] = 0
        _modeled_peaks.clear()
    for k in list(_counters):
        _counters[k] = 0


def note_modeled_peak(name, peak_bytes):
    """Record one compiled program's modeled peak HBM (argument + output
    + temp bytes from ``compiled.memory_analysis()``) — the ``modeled``
    leg of the headroom gauge. Keyed by program name; the newest compile
    of a name wins (per-signature history lives in the compile
    registry)."""
    with _ledger_lock:
        _modeled_peaks[str(name)] = int(peak_bytes)


def headroom(modeled_peak=None):
    """The ``memory.headroom`` gauge: modeled program peak vs the
    framework-side measured peak (``DeviceStats.peak_since_reset``) vs
    the device limit, from the newest ``stats()`` snapshot (cheap — no
    backend walk). Returns None when nothing is known yet."""
    with _ledger_lock:
        if modeled_peak is None and _modeled_peaks:
            modeled_peak = max(_modeled_peaks.values())
    snap = dict(_last_stats)
    dev_peak = max((v[1] for v in snap.values()), default=0)
    dev_limit = max((v[2] for v in snap.values()), default=0)
    if not snap and modeled_peak is None:
        return None
    out = {
        "modeled_peak_bytes": int(modeled_peak or 0),
        "device_peak_bytes": int(dev_peak),
        "device_limit_bytes": int(dev_limit),
    }
    if dev_limit:
        out["headroom_bytes"] = int(
            dev_limit - max(int(modeled_peak or 0), dev_peak))
    return out


def memory_metrics():
    """The storage-owned half of ``profiler.metrics()['memory']``: the
    ledger snapshot, the allocation-accounting counters (single owner —
    the account contract, counts with profiling off), the headroom
    gauge, and the leak-watchdog state."""
    out = {
        "ledger": ledger_metrics(),
        "alloc_fallbacks": _counters.get("alloc_fallbacks", 0),
        "empty_cache_calls": _counters.get("empty_cache_calls", 0),
    }
    hr = headroom()
    if hr is not None:
        out["headroom"] = hr
    try:
        from ._debug import memwatch
        out["memwatch"] = memwatch.stats()
    except Exception:
        pass
    return out
