"""Device memory / storage introspection.

TPU-native re-design of the reference storage layer (ref: src/storage/,
include/mxnet/storage.h:36-137). The reference implements its own pooled
allocators (GPUPooledStorageManager, pooled_storage_manager.h:52) because
cudaMalloc is slow; on TPU the PJRT runtime owns the HBM allocator (BFC-style
pooling lives below XLA), so the framework's job is *introspection and
control*, not reimplementation:

* per-device usage stats (≙ the pool counters the reference keeps),
* an explicit release hook (≙ ``Storage::ReleaseAll`` / ``MXStorageEmptyCache``)
  implemented by dropping framework references and forcing a GC,
* host-side pinned/shared-memory roles are covered by the data-IO stack
  (gluon DataLoader shared workers).
"""
from __future__ import annotations

import gc

from ._debug import locktrace as _locktrace

__all__ = ["DeviceStats", "stats", "total_bytes_in_use", "release_all",
           "empty_cache", "reset_peak"]

# Framework-side high-water mark per device, updated on every stats() call.
# PJRT's own peak_bytes_in_use is cumulative for the process and cannot be
# reset, so per-step peak deltas (profiler memory samples between steps)
# come from this re-derivable mark instead: reset_peak() rebases it to the
# current usage and the next samples grow it from there.
_hwm_lock = _locktrace.named_lock("storage.hwm")
_hwm = {}  # str(device) -> high-water bytes_in_use since last reset_peak()


class DeviceStats:
    """Memory stats for one device (≙ the pool counters in
    src/storage/pooled_storage_manager.h:61-115)."""

    def __init__(self, device, raw):
        self.device = device
        self.bytes_in_use = int(raw.get("bytes_in_use", 0))
        self.peak_bytes_in_use = int(raw.get("peak_bytes_in_use", 0))
        self.bytes_limit = int(raw.get("bytes_limit", 0))
        self.num_allocs = int(raw.get("num_allocs", 0))
        self.largest_alloc_size = int(raw.get("largest_alloc_size", 0))
        self.peak_since_reset = 0  # filled in by stats()
        self.raw = dict(raw)

    def __repr__(self):
        return ("DeviceStats(%s, in_use=%d, peak=%d, limit=%d)"
                % (self.device, self.bytes_in_use, self.peak_bytes_in_use,
                   self.bytes_limit))


def stats():
    """Per-device memory stats from PJRT. CPU devices may not report stats;
    they yield zeroed entries. Each call advances the framework-side
    high-water mark backing ``peak_since_reset`` (see ``reset_peak``)."""
    import jax
    out = []
    with _hwm_lock:
        for d in jax.devices():
            try:
                raw = d.memory_stats() or {}
            except Exception:
                raw = {}
            ds = DeviceStats(d, raw)
            key = str(d)
            mark = _hwm.get(key)
            if mark is None or ds.bytes_in_use > mark:
                mark = ds.bytes_in_use
                _hwm[key] = mark
            ds.peak_since_reset = mark
            out.append(ds)
    return out


def reset_peak():
    """Rebase the framework-side peak mark to current usage (per device),
    so ``DeviceStats.peak_since_reset`` measures the high-water mark of the
    window since this call — e.g. one training step between two profiler
    memory samples. PJRT's own ``peak_bytes_in_use`` is process-cumulative
    and stays untouched. Returns {str(device): rebased bytes_in_use}."""
    import jax
    out = {}
    with _hwm_lock:
        for d in jax.devices():
            try:
                raw = d.memory_stats() or {}
            except Exception:
                raw = {}
            key = str(d)
            _hwm[key] = int(raw.get("bytes_in_use", 0))
            out[key] = _hwm[key]
    return out


def total_bytes_in_use():
    return sum(s.bytes_in_use for s in stats())


def release_all():
    """Drop unreferenced device buffers (ref: Storage::ReleaseAll,
    include/mxnet/storage.h; MXStorageEmptyCache in the C API). PJRT frees a
    buffer when its last reference dies, so this forces a collection pass and
    deletes donated/aliased temporaries."""
    gc.collect()


empty_cache = release_all
