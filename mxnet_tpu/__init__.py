"""mxnet_tpu — a TPU-native deep learning framework.

A ground-up re-design of Apache MXNet 1.6's capability surface
(reference: Caenorst/incubator-mxnet, see SURVEY.md) for TPU hardware:
jax/XLA is the compute path (MXU-tiled matmuls, fused elementwise, ICI
collectives), the imperative NDArray/autograd/Gluon/Module APIs match the
reference so user code ports with ``import mxnet_tpu as mx`` and
``ctx=mx.tpu()``.

Layer map (vs SURVEY.md §1): storage/engine → XLA+PJRT runtime; operators →
mxnet_tpu/ops (pure jax); imperative+autograd → NDArray + vjp tape; CachedOp
→ jit'd hybridize; kvstore → mesh collectives (mxnet_tpu/kvstore, parallel);
C ABI + frontends → this Python package.
"""
from __future__ import annotations

__version__ = "1.6.0.tpu1"

from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, current_context,
                      num_gpus, num_tpus)
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray
# importing applies the MXTPU_MATMUL_PRECISION env policy (VERDICT r4 #3)
from .precision import (set_matmul_precision, get_matmul_precision,
                        matmul_precision)
from .attribute import AttrScope  # ref: mx.AttrScope (ctx_group scoping)

# re-export seed at top level like the reference (mx.random.seed exists too)


def seed(s):
    random.seed(s)


def waitall():
    nd.waitall()


# Heavier subsystems are imported lazily to keep `import mxnet_tpu` fast.
_LAZY = {
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "initializer": ".initializer",
    "init": ".initializer",
    "metric": ".metric",
    "lr_scheduler": ".lr_scheduler",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "io": ".io",
    "image": ".image",
    "symbol": ".symbol",
    "sym": ".symbol",
    "module": ".module",
    "mod": ".module",
    "model": ".model",
    "rnn": ".rnn",
    "callback": ".callback",
    "monitor": ".monitor",
    "profiler": ".profiler",
    "parallel": ".parallel",
    "models": ".models",
    "recordio": ".recordio",
    "runtime": ".runtime",
    "test_utils": ".test_utils",
    "util": ".util",
    "amp": ".contrib.amp",
    "contrib": ".contrib",
    "engine": ".engine",
    "executor": ".executor",
    "jit": ".jit",
    "numpy": ".numpy",
    "np": ".numpy",
    "numpy_extension": ".numpy_extension",
    "npx": ".numpy_extension",
    "lib_api": ".lib_api",
    "library": ".library",
    "storage": ".storage",
    "rtc": ".rtc",
    "visualization": ".visualization",
    "viz": ".visualization",
    "predictor": ".predictor",
    "name": ".name",
    "attribute": ".attribute",
    "kvstore_server": ".kvstore_server",
    "tensor_inspector": ".tensor_inspector",
    "operator": ".operator",
}


def __getattr__(name):
    import importlib
    if name in _LAZY:
        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def __dir__():
    return sorted(list(globals().keys()) + list(_LAZY.keys()))
