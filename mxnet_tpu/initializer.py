"""Weight initializers (ref: python/mxnet/initializer.py:57 Initializer and
the ~15 registered subclasses). Initializers fill host numpy buffers which are
then placed on device — keeping init off the TPU hot path.
"""
from __future__ import annotations

import math

import numpy as _np

from .random import host_rng as _host_rng

from .base import _Registry

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "FusedRNN", "Mixed", "get", "register", "create"]

_REG = _Registry("initializer")


def register(klass):
    _REG.register(klass.__name__.lower(), klass)
    return klass


# the reference registers plural aliases via @init.register decorators
# (ref: python/mxnet/initializer.py "zeros"/"ones" registry names)
_ALIASES = {"zeros": "zero", "ones": "one"}


def get(name):
    if isinstance(name, Initializer):
        return name
    return _REG.get(_ALIASES.get(name.lower(), name))()


def create(spec):
    """Initializer from a ``dumps()`` JSON spec, a plain registry name, or
    an Initializer instance (ref: initializer.py registry.create path used
    by the ``__init__`` variable attr)."""
    import json
    if isinstance(spec, Initializer):
        return spec
    if isinstance(spec, str) and spec.startswith("["):
        klass, kwargs = json.loads(spec)
        return _REG.get(_ALIASES.get(klass.lower(), klass.lower()))(**kwargs)
    return get(spec)


class InitDesc(str):
    """Name with attrs, ref: python/mxnet/initializer.py:37."""
    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base class. Subclasses override ``_init_weight``; dispatch by
    parameter-name suffix mirrors the reference (initializer.py __call__)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        from .ndarray import NDArray
        import jax.numpy as jnp
        # a variable-level init attr overrides the global initializer and
        # always runs its _init_weight (no suffix dispatch) — ref:
        # initializer.py Initializer.__call__ '__init__' attr branch
        spec = getattr(desc, "attrs", None) or {}
        override = spec.get("__init__")
        if isinstance(arr, NDArray):
            # asnumpy() of a jax buffer is a read-only view; copy for in-place
            host = _np.array(arr.asnumpy())
            if override:
                create(override)._init_weight(str(desc), host)
            else:
                self._init_weight_dispatch(str(desc), host)
            arr._data = jnp.asarray(host)
        elif override:
            create(override)._init_weight(str(desc), arr)
        else:
            self._init_weight_dispatch(str(desc), arr)

    def _init_weight_dispatch(self, name, arr):
        name = name.lower()
        if name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_weight(name, arr)

    def _init_bias(self, _, arr):
        arr[...] = 0.0

    def _init_gamma(self, _, arr):
        arr[...] = 1.0

    def _init_beta(self, _, arr):
        arr[...] = 0.0

    def _init_zero(self, _, arr):
        arr[...] = 0.0

    def _init_one(self, _, arr):
        arr[...] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def dumps(self):
        """JSON spec round-trippable through ``create()`` (ref:
        initializer.py Initializer.dumps)."""
        import json
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[...] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[...] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[...] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[...] = _host_rng().uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[...] = _host_rng().normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _host_rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _host_rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[...] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    """ref: python/mxnet/initializer.py Xavier."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires ndim >= 2")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[...] = _host_rng().uniform(-scale, scale, shape)
        else:
            arr[...] = _host_rng().normal(0, scale, shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = _np.zeros(arr.size, dtype=arr.dtype)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[...] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, _, arr):
        arr[...] = 0.0
        num_hidden = arr.shape[0] // 4
        arr[num_hidden:2 * num_hidden] = self.forget_bias


@register
class FusedRNN(Initializer):
    """Initialize a packed fused-RNN parameter vector
    (ref: initializer.py:715 FusedRNN). Walks the packed layout the `RNN`
    op consumes (ops/nn.py _rnn_unpack_params: weights layer-major with
    direction inner, then biases) and applies the inner initializer to each
    per-gate weight block; LSTM forget-gate bias rows get ``forget_bias``.
    """

    def __init__(self, init=None, num_hidden=None, num_layers=None,
                 mode="lstm", bidirectional=False, forget_bias=1.0):
        if isinstance(init, Initializer):
            init_spec = init.dumps()
        else:
            init_spec = init
        super().__init__(init=init_spec, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional, forget_bias=forget_bias)
        self._init = create(init_spec) if init_spec else None
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._ndir = 2 if bidirectional else 1
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        from .ops.nn import _RNN_GATES, rnn_packed_input_size
        g = _RNN_GATES[self._mode]
        h = self._num_hidden
        nd = self._ndir
        inner = self._init or Uniform(0.07)
        li = rnn_packed_input_size(arr.size, self._mode, h,
                                   self._num_layers, nd)
        off = 0
        for layer in range(self._num_layers):
            isz = li if layer == 0 else h * nd
            for _ in range(nd):
                for cols in (isz, h):  # i2h weight, then h2h weight
                    for j in range(g):
                        blk = arr[off:off + h * cols].reshape(h, cols)
                        inner._init_weight(name, blk)
                        arr[off:off + h * cols] = blk.ravel()
                        off += h * cols
        for layer in range(self._num_layers):
            for _ in range(nd):
                for _src in range(2):  # i2h bias, then h2h bias
                    for j in range(g):
                        val = self._forget_bias \
                            if (self._mode == "lstm" and j == 1) else 0.0
                        arr[off:off + h] = val
                        off += h
        assert off == arr.size, "packed fused-RNN parameter size mismatch"


class Mixed:
    """Patterns → initializers (ref: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for regex, init in self.map:
            if regex.search(str(name)):
                init(name, arr)
                return
        raise ValueError("no initializer matches %r" % name)

    def _init_weight_dispatch(self, name, arr):
        for regex, init in self.map:
            if regex.search(str(name)):
                init._init_weight_dispatch(name, arr)
                return
        raise ValueError("no initializer matches %r" % name)
