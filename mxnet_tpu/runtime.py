"""Runtime feature introspection (ref: python/mxnet/runtime.py,
src/libinfo.cc, include/mxnet/libinfo.h).

The reference exposes compile-time feature flags (CUDA, CUDNN, MKLDNN,
OPENCV, ...) through ``mx.runtime.Features``. Here features are detected at
import time from the live JAX/XLA runtime: which platforms (TPU/CPU) have
devices, whether pallas / distributed / native extensions are usable.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    """One runtime feature flag (ref: runtime.py:28 ctypes Feature struct)."""

    def __init__(self, name, enabled):
        self._name = name
        self._enabled = bool(enabled)

    @property
    def name(self):
        return self._name

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        return ("✔ {}" if self._enabled else "✖ {}").format(
            self._name)


def _detect():
    import jax
    feats = collections.OrderedDict()

    platforms = set()
    try:
        for d in jax.devices():
            platforms.add(d.platform)
    except Exception:
        pass
    feats["TPU"] = "tpu" in platforms
    feats["CPU"] = True
    feats["CUDA"] = "gpu" in platforms or "cuda" in platforms
    # bf16 is native on TPU; the reference's F16C flag analog
    feats["BF16"] = True
    feats["F16C"] = True
    feats["INT64_TENSOR_SIZE"] = True
    feats["SIGNAL_HANDLER"] = True

    try:
        from jax.experimental import pallas  # noqa: F401
        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    try:
        import jax.distributed  # noqa: F401
        feats["DIST_KVSTORE"] = True
    except Exception:
        feats["DIST_KVSTORE"] = False
    try:
        from . import _native
        feats["NATIVE_ENGINE"] = _native.available()
    except Exception:
        feats["NATIVE_ENGINE"] = False
    try:
        import jax.dlpack  # noqa: F401
        feats["DLPACK"] = True
    except Exception:
        feats["DLPACK"] = False
    # Data-IO features (host side, always built — pure python + native lib)
    feats["RECORDIO"] = True
    try:
        import PIL  # noqa: F401
        feats["JPEG_DECODE"] = True
    except Exception:
        feats["JPEG_DECODE"] = False
    return feats


class Features(collections.OrderedDict):
    """Map of feature name -> Feature (ref: runtime.py:72)."""

    instance = None

    def __init__(self):
        super().__init__([(n, Feature(n, e)) for n, e in _detect().items()])

    def __repr__(self):
        return "[" + ", ".join(map(repr, self.values())) + "]"

    def is_enabled(self, feature_name):
        """ref: runtime.py:86."""
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature %r does not exist" % (feature_name,))
        return self[feature_name].enabled


def feature_list():
    """List of runtime Feature objects (ref: runtime.py:57)."""
    return list(Features().values())
