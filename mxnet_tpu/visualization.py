"""Network visualization (ref: python/mxnet/visualization.py:
print_summary, plot_network)."""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def _param_count(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer table with output shapes and parameter counts
    (ref: visualization.py:38 print_summary). `shape` maps input names
    to shapes; without it output shapes print as '-'."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        # fractional positions (reference semantics); absolute column
        # stops pass through unchanged (ref: visualization.py:66)
        positions = [int(line_length * p) for p in positions]
    nodes = symbol._topo()
    out_shapes = {}
    arg_shapes = {}
    if shape:
        # one internals pass gives every node's output shape, including
        # the variable nodes that ARE the argument shapes
        internals = symbol.get_internals()
        _, int_out, _ = internals.infer_shape_partial(**shape)
        for (node, oi), s in zip(internals._outputs, int_out):
            out_shapes[(id(node), oi)] = s
            if node.is_variable() and s is not None:
                arg_shapes[node.name] = s

    def fmt(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line += str(f)
            line = line[:pos]
            line += " " * (pos - len(line))
        return line

    header = fmt(["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"])
    lines = ["_" * line_length, header, "=" * line_length]
    total = 0
    param_owner = set()
    for node in nodes:
        if node.is_variable():
            continue
        nparams = 0
        prevs = []
        for src, oi in node.inputs:
            if src.is_variable():
                s = arg_shapes.get(src.name)
                if s is not None and src.name not in param_owner \
                        and src.name not in (shape or {}):
                    nparams += _param_count(s)
                    param_owner.add(src.name)
                if src.name in (shape or {}):
                    prevs.append(src.name)
            else:
                prevs.append(src.name)
        total += nparams
        oshape = out_shapes.get((id(node), 0), "-")
        lines.append(fmt(["%s (%s)" % (node.name, node.op),
                          oshape, nparams, ",".join(prevs)]))
        lines.append("_" * line_length)
    lines.append("Total params: %d" % total)
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the symbol (ref: visualization.py:214
    plot_network). Returns the graphviz.Digraph; .render() writes it."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz package")
    node_attrs = node_attrs or {}
    base = {"shape": "box", "fixedsize": "true", "width": "1.3",
            "height": "0.8034", "style": "filled"}
    base.update(node_attrs)
    palette = {"FullyConnected": "#fb8072", "Convolution": "#fb8072",
               "Activation": "#ffffb3", "BatchNorm": "#bebada",
               "Pooling": "#80b1d3", "softmax": "#fccde5",
               "SoftmaxOutput": "#fccde5"}
    dot = Digraph(name=title, format=save_format)
    for node in symbol._topo():
        if node.is_variable():
            if hide_weights and node.name not in (shape or {}):
                continue
            dot.node(node.name, label=node.name, shape="oval",
                     fillcolor="#8dd3c7", style="filled")
            continue
        attrs = dict(base)
        attrs["fillcolor"] = palette.get(node.op, "#b3de69")
        dot.node(node.name, label="%s\n%s" % (node.name, node.op), **attrs)
        for src, _ in node.inputs:
            if src.is_variable() and hide_weights and \
                    src.name not in (shape or {}):
                continue
            dot.edge(src.name, node.name)
    return dot
