"""Global RNG state.

TPU-native re-design of the reference's random resource
(ref: src/resource.cc kRandom/kParallelRandom pools,
python/mxnet/random.py seed()). JAX PRNG is functional; this module owns a
global key that eager ops split from, and a *trace key* stack so that under a
jitted CachedOp the key is a traced argument (fold_in by call counter) rather
than a baked-in constant — keeping dropout/random ops fresh across steps.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_key", "push_trace_key", "pop_trace_key"]


class _RngState(threading.local):
    def __init__(self):
        self.key = jax.random.PRNGKey(0)
        self.trace_keys = []      # stack of (key, counter) used under tracing
        self.counter = 0


_STATE = _RngState()


def seed(seed_state, ctx="all"):
    """Set the global seed. ref: python/mxnet/random.py:34 (ctx arg kept for
    API parity; there is one logical RNG stream per host)."""
    _STATE.key = jax.random.PRNGKey(int(seed_state))
    _STATE.counter = 0


def next_key():
    """Return a fresh PRNG key. Under a trace scope, derive from the traced
    key so each eager-traced random op gets a distinct but traced key."""
    if _STATE.trace_keys:
        key, counter = _STATE.trace_keys[-1]
        _STATE.trace_keys[-1] = (key, counter + 1)
        return jax.random.fold_in(key, counter)
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


def current_key():
    return _STATE.key


def push_trace_key(key):
    _STATE.trace_keys.append((key, 0))


def pop_trace_key():
    _STATE.trace_keys.pop()
