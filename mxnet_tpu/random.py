"""Global RNG state.

TPU-native re-design of the reference's random resource
(ref: src/resource.cc kRandom/kParallelRandom pools,
python/mxnet/random.py seed()). JAX PRNG is functional; this module owns a
global (seed, counter) stream that eager ops derive keys from, and a
*trace key* stack so that under a jitted CachedOp the key is a traced
argument (fold_in by call counter) rather than a baked-in constant —
keeping dropout/random ops fresh across steps.

The global state is HOST-side integers, never jax arrays: if ``next_key``
is called inside an active trace with no pushed trace key (an eager-style
random op traced into someone's jit), the derived key is a tracer — which
must not be stored back into process state or it leaks out of the trace
(jax UnexpectedTracerError). Advancing a host counter sidesteps that
whole class of bug.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_key", "push_trace_key",
           "pop_trace_key"]


class _RngState(threading.local):
    def __init__(self):
        self.seed = 0
        self.counter = 0
        self.base_key = None      # concrete PRNGKey(seed), built lazily
        self.trace_keys = []      # stack of (key, counter) used under tracing


_STATE = _RngState()


import numpy as _host_np

# module-private host RNG for initializers' numpy draws: governed by
# mx.random.seed WITHOUT clobbering the user's global np.random stream
# (the reference likewise keeps its RNG separate from numpy's)
_HOST_RNG = _host_np.random.RandomState()


def host_rng():
    """Host-side numpy RandomState seeded by mx.random.seed (used by
    mxnet_tpu.initializer for parameter fills)."""
    return _HOST_RNG


def seed(seed_state, ctx="all"):
    """Set the global seed. ref: python/mxnet/random.py:34 (ctx arg kept for
    API parity; there is one logical RNG stream per host). Also seeds the
    private host RNG the initializers draw from, so parameter init is
    reproducible under mx.random.seed."""
    _STATE.seed = int(seed_state)
    _STATE.counter = 0
    _STATE.base_key = None
    _HOST_RNG.seed(int(seed_state) & 0xFFFFFFFF)


def _base_key():
    # cached: derived only from a host int, so it is always concrete and
    # safe to keep in process state even when first built inside a trace
    if _STATE.base_key is None:
        with jax.ensure_compile_time_eval():
            _STATE.base_key = jax.random.PRNGKey(_STATE.seed)
    return _STATE.base_key


def next_key():
    """Return a fresh PRNG key. Under a trace scope, derive from the traced
    key so each eager-traced random op gets a distinct but traced key."""
    if _STATE.trace_keys:
        key, counter = _STATE.trace_keys[-1]
        _STATE.trace_keys[-1] = (key, counter + 1)
        return jax.random.fold_in(key, counter)
    c = _STATE.counter
    _STATE.counter += 1  # host int: safe to advance inside any trace
    return jax.random.fold_in(_base_key(), c)


def current_key():
    """A key representing the current stream position WITHOUT consuming it;
    disjoint from the next_key stream (distinct fold_in branch), so draws
    from it never duplicate an eager op's draw."""
    return jax.random.fold_in(jax.random.fold_in(_base_key(),
                                                 _STATE.counter), 0x5EED)


def push_trace_key(key):
    _STATE.trace_keys.append((key, 0))


def pop_trace_key():
    _STATE.trace_keys.pop()
