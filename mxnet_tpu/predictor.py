"""Deployment predictor — the c_predict_api surface in Python.

ref: include/mxnet/c_predict_api.h (MXPredCreate :84, MXPredSetInput
:254, MXPredForward :263, MXPredGetOutput :289, MXPredReshape :214),
src/c_api/c_predict_api.cc. The reference ships this as a standalone C
ABI for embedding inference into applications; here the same
create/set_input/forward/get_output workflow binds the symbol to ONE
compiled XLA program, so repeated forwards at a fixed shape hit the
compile cache. A native C ABI wrapper over this module is the natural
round-2 extension of src/c_api.cc.
"""
from __future__ import annotations

import json

import numpy as _np

from . import ndarray as nd
from .context import cpu
from .executor import Executor  # noqa: F401  (re-export surface)
from .symbol import load_json as _sym_load_json

__all__ = ["Predictor"]


class Predictor:
    """Fixed-shape inference session (ref: c_predict_api.h:84
    MXPredCreate: symbol json + param bytes + input shapes)."""

    def __init__(self, symbol_json, param_raw_bytes=None, dev_type=None,
                 input_shapes=None, arg_params=None, aux_params=None,
                 output_keys=None):
        from .symbol.symbol import Symbol
        if isinstance(symbol_json, Symbol):
            self._symbol = symbol_json
        else:
            if isinstance(symbol_json, (bytes, bytearray)):
                symbol_json = symbol_json.decode("utf-8")
            if symbol_json.lstrip().startswith("{"):
                self._symbol = _sym_load_json(symbol_json)
            else:  # path
                with open(symbol_json) as f:
                    self._symbol = _sym_load_json(f.read())
        if output_keys:
            # partial outputs (ref: MXPredCreatePartialOut :155)
            outs = self._symbol.get_internals()
            self._symbol = outs[output_keys] if isinstance(output_keys, str) \
                else outs.select(*output_keys)

        if param_raw_bytes is not None:
            import io as _io
            # reference passes raw .params bytes (MXPredCreate param_bytes)
            loaded = nd.load(_io.BytesIO(param_raw_bytes))
            if not isinstance(loaded, dict):
                raise ValueError("param bytes must contain NAMED arrays "
                                 "('arg:name'/'aux:name' keys, the "
                                 "save_checkpoint format)")
            arg_params, aux_params = {}, {}
            for k, v in loaded.items():
                if k.startswith("arg:"):
                    arg_params[k[4:]] = v
                elif k.startswith("aux:"):
                    aux_params[k[4:]] = v
                else:
                    arg_params[k] = v
        self._arg_params = dict(arg_params or {})
        self._aux_params = dict(aux_params or {})
        self._ctx = dev_type if dev_type is not None else cpu()
        self._input_shapes = dict(input_shapes or {})
        self._inputs = {k: nd.zeros(v) for k, v in self._input_shapes.items()}
        self._outputs = None
        self._bind()

    def _bind(self):
        args = dict(self._arg_params)
        args.update(self._inputs)
        # infer shapes for auxiliary input vars the caller did not declare
        # (e.g. SoftmaxOutput's label at inference) and zero-fill them —
        # what the reference's predictor bind does through the executor's
        # shape inference (ref: src/c_api/c_predict_api.cc MXPredCreate)
        missing = [n for n in self._symbol.list_arguments() if n not in args]
        if missing:
            shapes = {k: tuple(v) for k, v in self._input_shapes.items()}
            arg_shapes, _, _ = self._symbol.infer_shape_partial(**shapes)
            batch = next(iter(self._input_shapes.values()))[0] \
                if self._input_shapes else 1
            for n, s in zip(self._symbol.list_arguments(), arg_shapes):
                if n in missing:
                    # un-inferable vars (loss labels — forward output does
                    # not depend on them) default to (batch,) zeros, the
                    # reference loss ops' default label shape
                    args[n] = nd.zeros(s if s is not None else (batch,))
        self._executor = self._symbol.bind(
            self._ctx, args=args, aux_states=self._aux_params,
            grad_req="null")

    # -- reference workflow -------------------------------------------------
    def set_input(self, key, data):
        """ref: MXPredSetInput (c_predict_api.h:254)."""
        if key not in self._inputs:
            raise KeyError("unknown input %r; declared inputs: %s"
                           % (key, sorted(self._inputs)))
        arr = data if isinstance(data, nd.NDArray) else nd.array(
            _np.asarray(data, "float32"))
        if tuple(arr.shape) != tuple(self._input_shapes[key]):
            raise ValueError("input %r shape %s != declared %s (use "
                             "reshape())" % (key, arr.shape,
                                             self._input_shapes[key]))
        self._executor.arg_dict[key]._data = arr._data

    def forward(self):
        """ref: MXPredForward (c_predict_api.h:263)."""
        self._outputs = self._executor.forward(is_train=False)

    def get_output_shape(self, index=0):
        """ref: MXPredGetOutputShape (c_predict_api.h:229) — from shape
        inference, without running the program."""
        if self._outputs is not None:
            return tuple(self._outputs[index].shape)
        shapes = {k: tuple(v) for k, v in self._input_shapes.items()}
        _, out_shapes, _ = self._symbol.infer_shape_partial(**shapes)
        return tuple(out_shapes[index])

    def get_output(self, index=0):
        """ref: MXPredGetOutput (c_predict_api.h:289) — host numpy copy."""
        if self._outputs is None:
            raise RuntimeError("call forward() before get_output()")
        return self._outputs[index].asnumpy()

    def reshape(self, new_input_shapes):
        """Rebind at new shapes (ref: MXPredReshape :214)."""
        self._input_shapes.update(new_input_shapes)
        self._inputs = {k: nd.zeros(v)
                        for k, v in self._input_shapes.items()}
        self._outputs = None
        self._bind()

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, dev_type=None,
                        output_keys=None):
        """Load '<prefix>-symbol.json' + '<prefix>-%04d.params'
        (the reference examples' standard deploy pairing)."""
        from .model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(sym, dev_type=dev_type, input_shapes=input_shapes,
                   arg_params=arg_params, aux_params=aux_params,
                   output_keys=output_keys)


# -- entry points for the native C predict ABI (src/c_predict_api.cc) --------
# Keep the argument types primitive (str/bytes/memoryview/lists) so the C
# side stays a thin CPython-call shim.

def _c_create(symbol_json, param_bytes, input_names, input_shapes):
    shapes = {n: tuple(int(d) for d in s)
              for n, s in zip(input_names, input_shapes)}
    return Predictor(symbol_json, param_raw_bytes=param_bytes,
                     input_shapes=shapes)


def _c_set_input(pred, key, buf):
    shape = pred._input_shapes[key]
    arr = _np.frombuffer(buf, dtype=_np.float32)
    if arr.size != int(_np.prod(shape)):
        raise ValueError("input %r: got %d elements, declared shape %s "
                         "needs %d" % (key, arr.size, shape,
                                       int(_np.prod(shape))))
    pred.set_input(key, _np.ascontiguousarray(arr.reshape(shape)))


def _c_get_output(pred, index):
    out = _np.ascontiguousarray(pred.get_output(index), dtype=_np.float32)
    return out.tobytes()


def _c_reshape(pred, input_names, input_shapes):
    # unspecified inputs keep their prior shape, like MXPredReshape and
    # Predictor.reshape
    shapes = dict(pred._input_shapes)
    shapes.update({n: tuple(int(d) for d in s)
                   for n, s in zip(input_names, input_shapes)})
    return Predictor(pred._symbol, dev_type=pred._ctx, input_shapes=shapes,
                     arg_params=pred._arg_params,
                     aux_params=pred._aux_params)
