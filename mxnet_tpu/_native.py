"""ctypes loader for the native C++ runtime library.

Analog of the reference's libmxnet.so discovery + ctypes FFI
(ref: python/mxnet/libinfo.py find_lib_path, python/mxnet/base.py _load_lib):
locates ``libmxnet_tpu.so`` next to the package, builds it from ``src/``
with g++ on first use if missing (the reference ships a prebuilt binary;
here the toolchain is part of the environment), and exposes the C ABI with
the reference's error convention — nonzero return → raise with
``MXTGetLastError()``.

Set ``MXNET_TPU_NO_NATIVE=1`` to force the pure-Python fallbacks.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess

from ._debug import locktrace as _locktrace
from .base import getenv as _getenv

_LIB = None
_LIB_LOCK = _locktrace.named_lock("native.lib")
_TRIED = False


def _lib_path():
    return os.path.join(os.path.dirname(__file__), "libmxnet_tpu.so")


def _src_dir():
    return os.path.join(os.path.dirname(os.path.dirname(__file__)), "src")


def _build():
    src = _src_dir()
    if not os.path.isdir(src):
        return False
    try:
        import fcntl
        # serialize concurrent first-use builds (forked dataloader workers,
        # pytest-xdist): without the lock a second process can CDLL a
        # half-linked .so while make is still writing it
        with open(os.path.join(src, ".build.lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if not os.path.exists(_lib_path()):
                    subprocess.run(["make", "-C", src], check=True,
                                   capture_output=True, timeout=120)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
        return os.path.exists(_lib_path())
    except Exception as e:  # compiler missing / build error → fallback
        logging.debug("native build failed: %s", e)
        return False


def _declare(lib):
    u64 = ctypes.c_uint64
    p = ctypes.c_void_p
    pp = ctypes.POINTER(ctypes.c_void_p)
    charpp = ctypes.POINTER(ctypes.c_char_p)
    intp = ctypes.POINTER(ctypes.c_int)
    u64p = ctypes.POINTER(u64)
    lib.MXTGetLastError.restype = ctypes.c_char_p
    for name, argtypes in [
        ("MXTRecordWriterCreate", [ctypes.c_char_p, pp]),
        ("MXTRecordWriterWrite", [p, ctypes.c_char_p, u64]),
        ("MXTRecordWriterTell", [p, u64p]),
        ("MXTRecordWriterFree", [p]),
        ("MXTRecordReaderCreate", [ctypes.c_char_p, pp]),
        ("MXTRecordReaderNext", [p, charpp, u64p, intp]),
        ("MXTRecordReaderSeek", [p, u64]),
        ("MXTRecordReaderTell", [p, u64p]),
        ("MXTRecordReaderFree", [p]),
        ("MXTThreadedReaderCreate",
         [ctypes.c_char_p, u64, ctypes.c_int, u64, pp]),
        ("MXTThreadedReaderNext", [p, charpp, u64p, intp]),
        ("MXTThreadedReaderReset", [p]),
        ("MXTThreadedReaderFree", [p]),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = ctypes.c_int
    # predict ABI (only present when built with python3-config available)
    u32 = ctypes.c_uint32
    u32p = ctypes.POINTER(u32)
    fp = ctypes.POINTER(ctypes.c_float)
    for name, argtypes in [
        ("MXTPredCreate",
         [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
          ctypes.c_int, u32, ctypes.POINTER(ctypes.c_char_p), u32p, u32p,
          pp]),
        ("MXTPredSetInput", [p, ctypes.c_char_p, fp, u32]),
        ("MXTPredForward", [p]),
        ("MXTPredGetOutputShape", [p, u32, ctypes.POINTER(u32p), u32p]),
        ("MXTPredGetOutput", [p, u32, fp, u32]),
        ("MXTPredReshape", [u32, ctypes.POINTER(ctypes.c_char_p), u32p,
                            u32p, p, pp]),
        ("MXTPredFree", [p]),
    ]:
        fn = getattr(lib, name, None)
        if fn is None:
            continue
        fn.argtypes = argtypes
        fn.restype = ctypes.c_int
    return lib


def get_lib():
    """The loaded native library, or None if unavailable/disabled."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LIB_LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if _getenv("MXNET_TPU_NO_NATIVE", "0") == "1":
            return None
        path = _lib_path()
        if not os.path.exists(path) and not _build():
            return None
        try:
            _LIB = _declare(ctypes.CDLL(path))
        except OSError as e:
            logging.debug("native load failed: %s", e)
            _LIB = None
    return _LIB


def check_call(ret):
    """ref: python/mxnet/base.py check_call."""
    if ret != 0:
        from .base import MXNetError
        raise MXNetError(get_lib().MXTGetLastError().decode("utf-8"))


def native_available():
    return get_lib() is not None


available = native_available  # runtime.Features probe name
