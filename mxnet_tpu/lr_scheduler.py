"""Learning-rate schedules.

Own-idiom, stateless redesign of the reference surface
(ref: python/mxnet/lr_scheduler.py, whose schedulers walk mutable
``base_lr``/``count`` state forward on every call). Here every schedule
is a closed-form function of the global update count::

    lr(t) = warmup(t)              while t is inside the warmup ramp
    lr(t) = _decayed(t)            afterwards

Closed form fits how the rate is consumed on TPU: the optimizer hands
``lr(t)`` to the jitted update step as a traced scalar operand
(optimizer/optimizer.py ``_get_lr``), so a changing rate never
recompiles — and resuming at step t after a checkpoint needs no replay
of the t-1 preceding calls that the reference's stateful walk relies on.

``base_lr`` stays a plain mutable attribute because the optimizer
re-points it after construction (``lr_scheduler.base_lr =
learning_rate``), matching the reference handshake.
"""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]

_log = logging.getLogger(__name__)


class LRScheduler:
    """Maps the optimizer's update counter to a learning rate.

    ``warmup_steps > 0`` prepends a ramp from ``warmup_begin_lr`` up to
    ``base_lr`` — linear per default, or flat at ``warmup_begin_lr``
    with ``warmup_mode="constant"``. Subclasses implement the
    post-warmup schedule as ``_decayed(num_update)``.
    """

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        if not isinstance(warmup_steps, int) or warmup_steps < 0:
            raise ValueError("warmup_steps must be a non-negative int, "
                             "got %r" % (warmup_steps,))
        if warmup_mode not in ("linear", "constant"):
            raise ValueError("warmup_mode must be 'linear' or 'constant', "
                             "got %r" % (warmup_mode,))
        if warmup_begin_lr > base_lr:
            raise ValueError("warmup ramps upward: warmup_begin_lr=%g "
                             "exceeds base_lr=%g" % (warmup_begin_lr,
                                                     base_lr))
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_mode = warmup_mode
        # frozen at construction like the reference: the optimizer's
        # later base_lr reassignment must not re-aim (or invert) a ramp
        # that was validated against the construction-time target
        self.warmup_final_lr = base_lr

    def get_warmup_lr(self, num_update):
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        ramp = num_update / float(self.warmup_steps)
        return self.warmup_begin_lr \
            + ramp * (self.warmup_final_lr - self.warmup_begin_lr)

    def _decayed(self, num_update):
        raise NotImplementedError(
            "%s must implement _decayed(num_update)"
            % type(self).__name__)

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self._decayed(num_update)


def _check_factor(factor):
    if factor > 1.0:
        raise ValueError("a decay factor > 1 would grow the rate, got %g"
                         % factor)


class FactorScheduler(LRScheduler):
    """``base_lr * factor**k``, stepping k once per ``step`` updates and
    flooring at ``stop_factor_lr``.

    Closed form ``k(t) = (t - 1) // step`` — the same k the reference
    walks with a count/while loop (ref: lr_scheduler.py:81).
    """

    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr,
                         warmup_mode)
        if step < 1:
            raise ValueError("step must be >= 1, got %r" % (step,))
        _check_factor(factor)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self._announced_k = 0

    def _decayed(self, num_update):
        k = max(0, (int(num_update) - 1) // self.step)
        lr = max(self.base_lr * self.factor ** k, self.stop_factor_lr)
        if k > self._announced_k:  # log each NEW decay level once
            self._announced_k = k
            _log.info("update %d: learning rate -> %.5e", num_update, lr)
        return lr


class MultiFactorScheduler(LRScheduler):
    """``base_lr * factor**k`` where k counts the milestones already
    passed (ref: lr_scheduler.py:131 walks the same milestones with a
    cursor index)."""

    def __init__(self, step, factor=1, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr,
                         warmup_mode)
        if not isinstance(step, (list, tuple)):
            # a scalar step otherwise dies with a TypeError mid-iteration
            # below; the reference's isinstance check names the contract
            raise ValueError("step must be a list or tuple of ints, got %r "
                             "(use FactorScheduler for a fixed interval)"
                             % (step,))
        if not step or any(s < 1 for s in step):
            raise ValueError("step must be a non-empty list of ints >= 1, "
                             "got %r" % (step,))
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError("milestones must strictly increase, got %r"
                             % (step,))
        _check_factor(factor)
        self.step = list(step)
        self.factor = factor
        self._announced_k = 0

    def _decayed(self, num_update):
        k = sum(1 for milestone in self.step if num_update > milestone)
        lr = self.base_lr * self.factor ** k
        if k > self._announced_k:
            self._announced_k = k
            _log.info("update %d: learning rate -> %.5e", num_update, lr)
        return lr


class _RampDown(LRScheduler):
    """Shared shape of the fixed-horizon decays: a monotone profile
    p(x) on x in [0, 1] scaled between base_lr and final_lr."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr,
                         warmup_mode)
        if not isinstance(max_update, int) or max_update < 1:
            raise ValueError("max_update must be a positive int, got %r"
                             % (max_update,))
        self.max_update = max_update
        self.final_lr = final_lr
        # frozen at construction, like the reference's base_lr_orig —
        # the optimizer's later base_lr assignment intentionally does
        # not rescale fixed-horizon schedules
        self.base_lr_orig = self.base_lr
        self.max_steps = max_update - warmup_steps

    def _profile(self, x):
        raise NotImplementedError

    def _decayed(self, num_update):
        x = (num_update - self.warmup_steps) / float(self.max_steps)
        span = self.base_lr_orig - self.final_lr
        return self.final_lr + span * self._profile(min(x, 1.0))


class PolyScheduler(_RampDown):
    """Polynomial ramp-down (1 - x)^pwr over max_update steps
    (ref: lr_scheduler.py:190)."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(max_update, base_lr, final_lr, warmup_steps,
                         warmup_begin_lr, warmup_mode)
        self.power = pwr

    def _profile(self, x):
        return (1.0 - x) ** self.power


class CosineScheduler(_RampDown):
    """Half-cosine ramp-down over max_update steps
    (ref: lr_scheduler.py:238)."""

    def _profile(self, x):
        return 0.5 * (1.0 + math.cos(math.pi * x))
