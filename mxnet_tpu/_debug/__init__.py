"""Runtime debugging aids (lock-order tracing, race detection).

Everything here is dormant unless its env gate is set — the framework
routes through these modules unconditionally, and the modules keep
their own disabled fast paths, so production runs pay (almost) nothing.
"""
from . import locktrace
from . import faultpoint
from . import flightrec

__all__ = ["locktrace", "faultpoint", "flightrec"]

# watchdog/goodput/memwatch/healthmon are imported lazily by their
# weld sites (fused_step, kvstore, storage) — importing them here
# would cycle through the profiler, which loads this package first.
