"""Step watchdog: hang and straggler detection for training loops.

Dean & Barroso's tail-at-scale argument applies with a vengeance to
synchronous training: one wedged collective or one straggling rank sets
the fleet's step time, and a job nobody is watching just silently runs
3x slow (or not at all). This module closes that gap with a progress
beacon + a daemon thread:

- The fused train step (``gluon/fused_step.py``) and
  ``parallel.elastic_train_loop`` bracket every step with
  ``step_begin()`` / ``step_end()`` (re-entrant: nested loops count the
  outermost step only).
- Completed non-warmup step durations feed a rolling-median window.
  Once ``MXTPU_WATCHDOG_MIN_SAMPLES`` steps completed, the watchdog is
  *armed* with threshold ``max(MXTPU_WATCHDOG_FACTOR * median,
  MXTPU_WATCHDOG_MIN_S)``.
- A daemon thread polls the in-flight step; one that exceeds the
  threshold is a **stall**: counted (``metrics()['watchdog']``), marked
  in the trace, and the flight recorder dumps a post-mortem shard —
  exactly once per stall, so a wedged collective yields one readable
  black box, not a dump storm.
- Completed steps beyond the threshold count as ``slow_steps``
  (stragglers that eventually finished).

Warm-up discipline: the first steps of a run (eager warming + the jit
compile) are slow by construction. They are excluded from the median
(the beacon flags them ``warmup=True``) and the watchdog is not armed
until enough representative steps completed — the compile step can
never false-positive. After arming, a *re*trace (shape churn) or a
wedged collective that exceeds the threshold does trip: that is the
black box working as intended.

The per-rank half: ``last_step()`` exposes the newest completed step's
(seq, duration) and the async-PS client rides it on every v1 heartbeat
(``kvstore_async``), so the PS server computes cross-rank skew and
names stragglers in ``metrics()['kvstore_server']`` and ``/metrics``
without any extra wire round trip.

Env knobs (docs/ENV_VARS.md): ``MXTPU_WATCHDOG`` (default 1),
``MXTPU_WATCHDOG_FACTOR`` (default 8), ``MXTPU_WATCHDOG_MIN_S``
(default 5), ``MXTPU_WATCHDOG_POLL_S`` (default min_s/5, clamped to
[0.02, 1]), ``MXTPU_WATCHDOG_WINDOW`` (default 32),
``MXTPU_WATCHDOG_MIN_SAMPLES`` (default 3).
"""
from __future__ import annotations

import collections
import os
import statistics
import threading
import time

from . import flightrec as _flightrec
from . import goodput as _goodput
from . import locktrace as _locktrace
from ..base import getenv as _getenv

__all__ = [
    "ENABLED", "configure", "reset", "reset_window", "step_begin",
    "step_end", "last_step", "threshold_s", "stats", "check_now",
]


def _envf(name, default):
    try:
        return float(_getenv(name, "") or default)
    except ValueError:
        return default


ENABLED = _getenv("MXTPU_WATCHDOG", "1") not in ("0", "false",
                                                        "off")

_lock = _locktrace.named_lock("watchdog.state")
_cfg = {}        # factor/min_s/poll_s/window/min_samples (see _defaults)
_seq = 0         # beacon sequence: id of the newest step_begin
_depth = 0       # re-entrancy: nested loops track the OUTER step
_inflight = None  # (seq, monotonic start) of the running outer step
_inflight_warmup = False  # a nested warmup end taints the outer step
_inflight_mode = None     # nested step's execution mode (fused_step)
_last = None     # (seq, dur_s) of the newest COMPLETED step
_tripped = None  # seq already dumped for — exactly one dump per stall
_stats = {"steps": 0, "warmup_steps": 0, "stalls": 0, "dumps": 0,
          "slow_steps": 0, "armed": 0, "median_s": 0.0,
          "threshold_s": 0.0, "last_stall_step": -1,
          "last_stall_elapsed_s": 0.0, "window_resets": 0}
_thread = None
_stop = None


def _defaults():
    return {
        "factor": _envf("MXTPU_WATCHDOG_FACTOR", 8.0),
        "min_s": _envf("MXTPU_WATCHDOG_MIN_S", 5.0),
        "poll_s": _envf("MXTPU_WATCHDOG_POLL_S", 0.0),  # 0 = derive
        "window": int(_envf("MXTPU_WATCHDOG_WINDOW", 32)),
        "min_samples": int(_envf("MXTPU_WATCHDOG_MIN_SAMPLES", 3)),
    }


_cfg.update(_defaults())

# completed non-warmup durations; sized AFTER the env knobs are read so
# MXTPU_WATCHDOG_WINDOW applies from import, not only after reset()
_durs = collections.deque(maxlen=max(1, _cfg["window"]))


def configure(factor=None, min_s=None, poll_s=None, window=None,
              min_samples=None, enabled=None):
    """Override the env-derived knobs at runtime (tests, notebooks)."""
    global ENABLED, _durs
    with _lock:
        if factor is not None:
            _cfg["factor"] = float(factor)
        if min_s is not None:
            _cfg["min_s"] = float(min_s)
        if poll_s is not None:
            _cfg["poll_s"] = float(poll_s)
        if min_samples is not None:
            _cfg["min_samples"] = int(min_samples)
        if window is not None:
            _cfg["window"] = int(window)
            _durs = collections.deque(_durs, maxlen=max(1, int(window)))
    if enabled is not None:
        ENABLED = bool(enabled)


def reset():
    """Stop the poller and clear all state; knobs re-read from the env
    (test isolation)."""
    global _seq, _depth, _inflight, _last, _tripped, _thread, _stop
    global ENABLED, _durs, _inflight_warmup, _inflight_mode
    with _lock:
        stop, thread = _stop, _thread
        _thread = _stop = None
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5)
    with _lock:
        _seq = _depth = 0
        _inflight = _last = _tripped = None
        _inflight_warmup = False
        _inflight_mode = None
        _cfg.clear()
        _cfg.update(_defaults())
        _durs = collections.deque(maxlen=_cfg["window"])
        for k in _stats:
            _stats[k] = -1 if k == "last_stall_step" else 0
        _stats["median_s"] = _stats["threshold_s"] = 0.0
        _stats["last_stall_elapsed_s"] = 0.0
    ENABLED = _getenv("MXTPU_WATCHDOG", "1") not in (
        "0", "false", "off")


def reset_window():
    """Drop the rolling step-time median window — nothing else: the
    poller, cumulative stats and the in-flight beacon survive.

    Called by ``elastic_train_loop`` on every reshard/restore: step
    durations measured at the OLD world size pollute the median after a
    resize — a shrunk world's slower cadence against a fast stale
    median trips false stalls, and a grown world's fast cadence against
    a slow stale median masks real ones. Clearing the window disarms
    the watchdog until ``min_samples`` fresh steps at the NEW cadence
    complete (the same warm-up discipline the compile step gets)."""
    with _lock:
        _durs.clear()
        _stats["window_resets"] += 1


def _poll_interval():
    p = _cfg["poll_s"]
    if p > 0:
        return p
    return min(1.0, max(0.02, _cfg["min_s"] / 5.0))


def _median_locked():
    return statistics.median(_durs) if _durs else 0.0


def threshold_s():
    """Current stall threshold in seconds, or ``None`` while unarmed
    (not enough representative completed steps yet)."""
    with _lock:
        if len(_durs) < _cfg["min_samples"]:
            return None
        return max(_cfg["factor"] * _median_locked(), _cfg["min_s"])


def last_step():
    """(seq, duration_s) of the newest completed step, or None — the
    per-rank gauge the kvstore heartbeat carries to the PS server."""
    return _last


def stats():
    """Flat JSON-safe snapshot — ``profiler.metrics()['watchdog']``."""
    with _lock:
        out = dict(_stats)
        out["median_s"] = round(_median_locked(), 6)
        thr = (max(_cfg["factor"] * _median_locked(), _cfg["min_s"])
               if len(_durs) >= _cfg["min_samples"] else 0.0)
        out["threshold_s"] = round(thr, 6)
        out["armed"] = int(len(_durs) >= _cfg["min_samples"])
        out["enabled"] = int(ENABLED)
    return out


def step_begin():
    """Mark the start of a training step (re-entrant). Starts the
    poller thread lazily on first use when the watchdog is enabled."""
    global _seq, _depth, _inflight, _inflight_warmup, _inflight_mode
    if not ENABLED:
        return
    with _lock:
        _depth += 1
        if _depth > 1:
            return  # nested loop: the outer step owns the beacon
        _seq += 1
        _inflight = (_seq, time.monotonic())
        _inflight_warmup = False
        _inflight_mode = None
    _ensure_thread()


def step_end(warmup=False, mode=None):
    """Mark the end of the innermost-begun step. ``warmup=True`` steps
    (eager warming, jit compile, fallbacks) complete the beacon but do
    not feed the median — they are not representative of steady state.
    A nested warmup end taints the whole outer step: when
    ``elastic_train_loop``'s beacon wraps a fused step whose inner end
    reported warmup, the outer completion is warmup too (the outer
    duration CONTAINS the compile). ``mode`` carries the fused step's
    execution mode (``fused``/``compile``/``eager-warming``/
    ``fallback:*``) so the goodput run ledger can attribute the step's
    wall time to compute vs compile vs host overhead — a nested mode
    taints the outer completion the same way warmup does.

    The completed step feeds ``goodput.note_step`` AFTER this module's
    lock is released — and that feed is itself one lock-free
    GIL-atomic append riding the beacon's own clock reads."""
    global _depth, _inflight, _last, _inflight_warmup, _inflight_mode
    if not ENABLED:
        return
    done = None
    with _lock:
        if _depth == 0:
            return
        _depth -= 1
        if warmup:
            _inflight_warmup = True
        if mode is not None:
            _inflight_mode = mode
        if _depth > 0 or _inflight is None:
            return
        seq, t0 = _inflight
        _inflight = None
        warmup = warmup or _inflight_warmup
        mode = mode if mode is not None else _inflight_mode
        _inflight_warmup = False
        _inflight_mode = None
        dur = time.monotonic() - t0
        _last = (seq, dur)
        done = (t0, dur, warmup, mode)
        if warmup:
            _stats["warmup_steps"] += 1
        else:
            _stats["steps"] += 1
            thr = (max(_cfg["factor"] * _median_locked(),
                       _cfg["min_s"])
                   if len(_durs) >= _cfg["min_samples"] else None)
            _durs.append(dur)
            if thr is not None and dur > thr and seq != _tripped:
                # finished, but way beyond the envelope: a straggler
                # (the in-flight poller may already have dumped for it)
                _stats["slow_steps"] += 1
    if _goodput.OPEN:
        # the goodput feed rides the beacon's OWN clock reads (t0/dur
        # above): the run ledger costs this one call per STEP, nothing
        # per op (BENCH_MODEL=goodput_overhead prices it)
        _goodput.note_step(done[0], done[1], warmup=done[2],
                           mode=done[3])


def check_now():
    """Force one poll pass synchronously (tests; also useful from a
    debugger). Returns True when it tripped."""
    return _check(time.monotonic())


def _check(now):
    global _tripped
    with _lock:
        if _inflight is None or len(_durs) < _cfg["min_samples"]:
            return False
        seq, t0 = _inflight
        if seq == _tripped:
            return False
        thr = max(_cfg["factor"] * _median_locked(), _cfg["min_s"])
        elapsed = now - t0
        if elapsed <= thr:
            return False
        _tripped = seq
        _stats["stalls"] += 1
        _stats["last_stall_step"] = seq
        _stats["last_stall_elapsed_s"] = round(elapsed, 3)
        median = _median_locked()
    from .. import profiler as _profiler
    _profiler.marker("watchdog:stall",
                     args={"step": seq, "elapsed_s": round(elapsed, 3),
                           "threshold_s": round(thr, 3)},
                     category="watchdog")
    path = _flightrec.dump(
        "watchdog",
        extra={"step": seq, "elapsed_s": round(elapsed, 3),
               "threshold_s": round(thr, 3),
               "median_step_s": round(median, 6)},
        swallow=True)
    if path is not None:
        with _lock:
            _stats["dumps"] += 1
    return True


def _loop(stop):
    while not stop.wait(_poll_interval()):
        try:
            _check(time.monotonic())
            # drain the goodput ledger's hot-path mailboxes off the
            # training thread (the PR 12 drain-on-whoever-asks idiom)
            _goodput.fold_pending()
        except Exception:
            pass  # the watchdog must never take the training loop down


def _ensure_thread():
    global _thread, _stop
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _stop = threading.Event()
        _thread = threading.Thread(target=_loop, args=(_stop,),
                                   daemon=True, name="mxtpu-watchdog")
        # start() UNDER the lock: it does not wait for the thread body
        # (which takes the lock itself), and a concurrent step_begin
        # must never observe a created-but-unstarted (is_alive()
        # False) thread and orphan it with a second poller
        _thread.start()


# surfaces as metrics()['watchdog'] and a dumps() provider line;
# registered here (watchdog is imported by fused_step/kvstore, after
# the profiler module is fully loaded — no cycle)
from .. import profiler as _profiler  # noqa: E402

_profiler.register_stats_provider("watchdog", stats)
