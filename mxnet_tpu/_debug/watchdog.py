"""Step watchdog: hang and straggler detection for training loops.

Dean & Barroso's tail-at-scale argument applies with a vengeance to
synchronous training: one wedged collective or one straggling rank sets
the fleet's step time, and a job nobody is watching just silently runs
3x slow (or not at all). This module closes that gap with a progress
beacon + a daemon thread:

- The fused train step (``gluon/fused_step.py``) and
  ``parallel.elastic_train_loop`` bracket every step with
  ``step_begin()`` / ``step_end()`` (re-entrant: nested loops count the
  outermost step only).
- Completed non-warmup step durations feed rolling-median windows
  keyed by the step's compile-signature tag (``None`` for untagged
  beacons): two interleaved cadences (train vs eval) each keep an
  honest median instead of contaminating one mixed window. Once any
  signature has ``MXTPU_WATCHDOG_MIN_SAMPLES`` completed steps, the
  watchdog is *armed* with threshold ``max(MXTPU_WATCHDOG_FACTOR *
  slowest_signature_median, MXTPU_WATCHDOG_MIN_S)`` (the in-flight
  step's signature is unknown, so the envelope tracks the slowest
  legitimate cadence); a COMPLETED step is judged a straggler against
  its own signature's median.
- A daemon thread polls the in-flight step; one that exceeds the
  threshold is a **stall**: counted (``metrics()['watchdog']``), marked
  in the trace, and the flight recorder dumps a post-mortem shard —
  exactly once per stall, so a wedged collective yields one readable
  black box, not a dump storm.
- Completed steps beyond the threshold count as ``slow_steps``
  (stragglers that eventually finished).

Warm-up discipline: the first steps of a run (eager warming + the jit
compile) are slow by construction. They are excluded from the median
(the beacon flags them ``warmup=True``) and the watchdog is not armed
until enough representative steps completed — the compile step can
never false-positive. After arming, a *re*trace (shape churn) or a
wedged collective that exceeds the threshold does trip: that is the
black box working as intended.

The per-rank half: ``last_step()`` exposes the newest completed step's
(seq, duration) and the async-PS client rides it on every v1 heartbeat
(``kvstore_async``), so the PS server computes cross-rank skew and
names stragglers in ``metrics()['kvstore_server']`` and ``/metrics``
without any extra wire round trip.

Env knobs (docs/ENV_VARS.md): ``MXTPU_WATCHDOG`` (default 1),
``MXTPU_WATCHDOG_FACTOR`` (default 8), ``MXTPU_WATCHDOG_MIN_S``
(default 5), ``MXTPU_WATCHDOG_POLL_S`` (default min_s/5, clamped to
[0.02, 1]), ``MXTPU_WATCHDOG_WINDOW`` (default 32),
``MXTPU_WATCHDOG_MIN_SAMPLES`` (default 3).
"""
from __future__ import annotations

import collections
import os
import statistics
import threading
import time

from . import flightrec as _flightrec
from . import goodput as _goodput
from . import locktrace as _locktrace
from ..base import getenv as _getenv

__all__ = [
    "ENABLED", "configure", "reset", "reset_window", "step_begin",
    "step_end", "last_step", "threshold_s", "stats", "check_now",
]


def _envf(name, default):
    try:
        return float(_getenv(name, "") or default)
    except ValueError:
        return default


ENABLED = _getenv("MXTPU_WATCHDOG", "1") not in ("0", "false",
                                                        "off")

_lock = _locktrace.named_lock("watchdog.state")
_cfg = {}        # factor/min_s/poll_s/window/min_samples (see _defaults)
_seq = 0         # beacon sequence: id of the newest step_begin
_depth = 0       # re-entrancy: nested loops track the OUTER step
_inflight = None  # (seq, monotonic start) of the running outer step
_inflight_warmup = False  # a nested warmup end taints the outer step
_inflight_mode = None     # nested step's execution mode (fused_step)
_inflight_sig = None      # nested step's compile-signature tag
_last = None     # (seq, dur_s) of the newest COMPLETED step
_tripped = None  # seq already dumped for — exactly one dump per stall
_stats = {"steps": 0, "warmup_steps": 0, "stalls": 0, "dumps": 0,
          "slow_steps": 0, "armed": 0, "median_s": 0.0,
          "threshold_s": 0.0, "last_stall_step": -1,
          "last_stall_elapsed_s": 0.0, "window_resets": 0,
          "sig_windows": 0}
_thread = None
_stop = None

# a run that churns through signatures must not leak windows; past the
# cap everything clears (the _CACHE_CAP one-shot idiom) and the
# watchdog re-arms from fresh samples
_MAX_SIG_WINDOWS = 64


def _defaults():
    return {
        "factor": _envf("MXTPU_WATCHDOG_FACTOR", 8.0),
        "min_s": _envf("MXTPU_WATCHDOG_MIN_S", 5.0),
        "poll_s": _envf("MXTPU_WATCHDOG_POLL_S", 0.0),  # 0 = derive
        "window": int(_envf("MXTPU_WATCHDOG_WINDOW", 32)),
        "min_samples": int(_envf("MXTPU_WATCHDOG_MIN_SAMPLES", 3)),
    }


_cfg.update(_defaults())

# completed non-warmup durations, keyed by the step's compile-signature
# tag (None = untagged: eager/elastic beacons). ISSUE 17 satellite: a
# single mixed window let a second hot signature (eval vs train) skew
# the stall envelope — a majority of fast eval steps dragged the median
# down until every train step read as a straggler. Per-signature
# windows keep each cadence's own median honest; the stall envelope is
# the SLOWEST armed cadence (conservative: interleaving can never
# false-trip), and a completed step is judged against its OWN window.
_durs = {}  # mxlint: disable=MX003 (mutated only from _win_locked/configure/reset, all run under _lock — the helper is named *_locked for exactly this contract)


def _win_locked(sig):
    w = _durs.get(sig)
    if w is None:
        if len(_durs) >= _MAX_SIG_WINDOWS:
            _durs.clear()
        w = _durs[sig] = collections.deque(
            maxlen=max(1, _cfg["window"]))
    return w


def _armed_medians_locked():
    return [statistics.median(w) for w in _durs.values()
            if len(w) >= _cfg["min_samples"]]


def configure(factor=None, min_s=None, poll_s=None, window=None,
              min_samples=None, enabled=None):
    """Override the env-derived knobs at runtime (tests, notebooks)."""
    global ENABLED
    with _lock:
        if factor is not None:
            _cfg["factor"] = float(factor)
        if min_s is not None:
            _cfg["min_s"] = float(min_s)
        if poll_s is not None:
            _cfg["poll_s"] = float(poll_s)
        if min_samples is not None:
            _cfg["min_samples"] = int(min_samples)
        if window is not None:
            _cfg["window"] = int(window)
            for sig in list(_durs):
                _durs[sig] = collections.deque(
                    _durs[sig], maxlen=max(1, int(window)))
    if enabled is not None:
        ENABLED = bool(enabled)


def reset():
    """Stop the poller and clear all state; knobs re-read from the env
    (test isolation)."""
    global _seq, _depth, _inflight, _last, _tripped, _thread, _stop
    global ENABLED, _inflight_warmup, _inflight_mode, _inflight_sig
    with _lock:
        stop, thread = _stop, _thread
        _thread = _stop = None
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5)
    with _lock:
        _seq = _depth = 0
        _inflight = _last = _tripped = None
        _inflight_warmup = False
        _inflight_mode = None
        _inflight_sig = None
        _cfg.clear()
        _cfg.update(_defaults())
        _durs.clear()
        for k in _stats:
            _stats[k] = -1 if k == "last_stall_step" else 0
        _stats["median_s"] = _stats["threshold_s"] = 0.0
        _stats["last_stall_elapsed_s"] = 0.0
    ENABLED = _getenv("MXTPU_WATCHDOG", "1") not in (
        "0", "false", "off")


def reset_window():
    """Drop the rolling step-time median window — nothing else: the
    poller, cumulative stats and the in-flight beacon survive.

    Called by ``elastic_train_loop`` on every reshard/restore: step
    durations measured at the OLD world size pollute the median after a
    resize — a shrunk world's slower cadence against a fast stale
    median trips false stalls, and a grown world's fast cadence against
    a slow stale median masks real ones. Clearing the window disarms
    the watchdog until ``min_samples`` fresh steps at the NEW cadence
    complete (the same warm-up discipline the compile step gets).
    Clears EVERY signature's window — a reshard changes them all."""
    with _lock:
        _durs.clear()
        _stats["window_resets"] += 1


def _poll_interval():
    p = _cfg["poll_s"]
    if p > 0:
        return p
    return min(1.0, max(0.02, _cfg["min_s"] / 5.0))


def _median_locked():
    """The stall-envelope baseline: the SLOWEST armed signature's
    median. An in-flight step carries no signature (it is not known
    until dispatch returns), so the envelope must accommodate the
    slowest legitimate cadence — a fast eval window can never shrink
    it under the train cadence (the cross-contamination bug this
    keys-by-signature split fixes)."""
    meds = _armed_medians_locked()
    return max(meds) if meds else 0.0


def threshold_s():
    """Current stall threshold in seconds, or ``None`` while unarmed
    (no signature has enough representative completed steps yet)."""
    with _lock:
        meds = _armed_medians_locked()
        if not meds:
            return None
        return max(_cfg["factor"] * max(meds), _cfg["min_s"])


def last_step():
    """(seq, duration_s) of the newest completed step, or None — the
    per-rank gauge the kvstore heartbeat carries to the PS server."""
    return _last


def stats():
    """Flat JSON-safe snapshot — ``profiler.metrics()['watchdog']``."""
    with _lock:
        out = dict(_stats)
        meds = _armed_medians_locked()
        out["median_s"] = round(max(meds), 6) if meds else 0.0
        thr = (max(_cfg["factor"] * max(meds), _cfg["min_s"])
               if meds else 0.0)
        out["threshold_s"] = round(thr, 6)
        out["armed"] = int(bool(meds))
        out["sig_windows"] = len(_durs)
        out["enabled"] = int(ENABLED)
    return out


def step_begin():
    """Mark the start of a training step (re-entrant). Starts the
    poller thread lazily on first use when the watchdog is enabled."""
    global _seq, _depth, _inflight, _inflight_warmup, _inflight_mode
    global _inflight_sig
    if not ENABLED:
        return
    with _lock:
        _depth += 1
        if _depth > 1:
            return  # nested loop: the outer step owns the beacon
        _seq += 1
        _inflight = (_seq, time.monotonic())
        _inflight_warmup = False
        _inflight_mode = None
        _inflight_sig = None
    _ensure_thread()


def step_end(warmup=False, mode=None, sig=None):
    """Mark the end of the innermost-begun step. ``warmup=True`` steps
    (eager warming, jit compile, fallbacks) complete the beacon but do
    not feed the median — they are not representative of steady state.
    A nested warmup end taints the whole outer step: when
    ``elastic_train_loop``'s beacon wraps a fused step whose inner end
    reported warmup, the outer completion is warmup too (the outer
    duration CONTAINS the compile). ``mode`` carries the fused step's
    execution mode (``fused``/``compile``/``eager-warming``/
    ``fallback:*``) so the goodput run ledger can attribute the step's
    wall time to compute vs compile vs host overhead — a nested mode
    taints the outer completion the same way warmup does. ``sig`` is
    the executing program's compile-signature tag (fused steps only):
    it keys the rolling window this completion feeds, and it rides the
    goodput/perfmodel feeds as one extra tuple field — no new clock
    reads (ISSUE 17; ``BENCH_MODEL=perf_attrib`` prices it).

    The completed step feeds ``goodput.note_step`` and
    ``perfmodel.note_step`` AFTER this module's lock is released — each
    feed is one lock-free GIL-atomic append riding the beacon's own
    clock reads."""
    global _depth, _inflight, _last, _inflight_warmup, _inflight_mode
    global _inflight_sig
    if not ENABLED:
        return
    done = None
    with _lock:
        if _depth == 0:
            return
        _depth -= 1
        if warmup:
            _inflight_warmup = True
        if mode is not None:
            _inflight_mode = mode
        if sig is not None:
            _inflight_sig = sig
        if _depth > 0 or _inflight is None:
            return
        seq, t0 = _inflight
        _inflight = None
        warmup = warmup or _inflight_warmup
        mode = mode if mode is not None else _inflight_mode
        sig = sig if sig is not None else _inflight_sig
        _inflight_warmup = False
        _inflight_mode = None
        _inflight_sig = None
        dur = time.monotonic() - t0
        _last = (seq, dur)
        done = (t0, dur, warmup, mode, sig)
        if warmup:
            _stats["warmup_steps"] += 1
        else:
            _stats["steps"] += 1
            # the straggler verdict compares this completion against
            # its OWN signature's window (threshold BEFORE appending,
            # so a step can't vote itself normal)
            w = _win_locked(sig)
            thr = (max(_cfg["factor"] * statistics.median(w),
                       _cfg["min_s"])
                   if len(w) >= _cfg["min_samples"] else None)
            w.append(dur)
            if thr is not None and dur > thr and seq != _tripped:
                # finished, but way beyond the envelope: a straggler
                # (the in-flight poller may already have dumped for it)
                _stats["slow_steps"] += 1
    if _goodput.OPEN:
        # the goodput feed rides the beacon's OWN clock reads (t0/dur
        # above): the run ledger costs this one call per STEP, nothing
        # per op (BENCH_MODEL=goodput_overhead prices it)
        _goodput.note_step(done[0], done[1], warmup=done[2],
                           mode=done[3], sig=done[4])
    if done[4] is not None and not done[2] and _perfmodel.ENABLED:
        # the roofline join's measured side: same discipline — the
        # tagged duration this beacon already computed, one append
        _perfmodel.note_step(done[4], done[1])


def check_now():
    """Force one poll pass synchronously (tests; also useful from a
    debugger). Returns True when it tripped."""
    return _check(time.monotonic())


def _check(now):
    global _tripped
    with _lock:
        if _inflight is None or not _armed_medians_locked():
            return False
        seq, t0 = _inflight
        if seq == _tripped:
            return False
        thr = max(_cfg["factor"] * _median_locked(), _cfg["min_s"])
        elapsed = now - t0
        if elapsed <= thr:
            return False
        _tripped = seq
        _stats["stalls"] += 1
        _stats["last_stall_step"] = seq
        _stats["last_stall_elapsed_s"] = round(elapsed, 3)
        median = _median_locked()
    from .. import profiler as _profiler
    _profiler.marker("watchdog:stall",
                     args={"step": seq, "elapsed_s": round(elapsed, 3),
                           "threshold_s": round(thr, 3)},
                     category="watchdog")
    path = _flightrec.dump(
        "watchdog",
        extra={"step": seq, "elapsed_s": round(elapsed, 3),
               "threshold_s": round(thr, 3),
               "median_step_s": round(median, 6)},
        swallow=True)
    if path is not None:
        with _lock:
            _stats["dumps"] += 1
    return True


def _loop(stop):
    while not stop.wait(_poll_interval()):
        try:
            _check(time.monotonic())
            # drain the goodput/perfmodel hot-path mailboxes off the
            # training thread (the PR 12 drain-on-whoever-asks idiom);
            # collapse dumps fire here, never on the step path
            _goodput.fold_pending()
            _perfmodel.fold_pending()
        except Exception:
            pass  # the watchdog must never take the training loop down


def _ensure_thread():
    global _thread, _stop
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _stop = threading.Event()
        _thread = threading.Thread(target=_loop, args=(_stop,),
                                   daemon=True, name="mxtpu-watchdog")
        # start() UNDER the lock: it does not wait for the thread body
        # (which takes the lock itself), and a concurrent step_begin
        # must never observe a created-but-unstarted (is_alive()
        # False) thread and orphan it with a second poller
        _thread.start()


# surfaces as metrics()['watchdog'] and a dumps() provider line;
# registered here (watchdog is imported by fused_step/kvstore, after
# the profiler module is fully loaded — no cycle). perfmodel is a
# bottom import too: it imports _envf from THIS module, so a top
# import would race module initialization whichever side loads first.
from .. import profiler as _profiler  # noqa: E402
from . import perfmodel as _perfmodel  # noqa: E402

_profiler.register_stats_provider("watchdog", stats)
