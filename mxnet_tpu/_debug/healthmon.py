"""Training-health plane: in-graph tensor-health sentinels, per-layer
grad/weight statistics, cross-rank SDC digests, and anomaly-triggered
post-mortems — the numerics sibling of watchdog (time), memwatch
(memory) and goodput (wall-clock).

The observability stack can say where the time and the memory went, but
a NaN'd gradient, an exploding layer, or a rank silently computing
wrong numbers (silent data corruption — the costliest failure mode the
MegaScale and Meta SDC studies report at fleet scale) produces no
signal until the loss curve is already garbage. The reference ships
exactly this surface (ref: python/mxnet/monitor.py Monitor,
src/common/tensor_inspector.h NaN/inf checks) but as Python forward
hooks and host-side array walks — both silently bypassed by the
hybridized and fused-step paths every real run uses. This module puts
the checks INSIDE the donated program instead:

- **In-graph sentinels** (:func:`graph_summary`): the fused step
  (``gluon/fused_step.py``), when ``MXTPU_HEALTH=1``, threads a tiny
  health summary out of the donated program — per-bucket L2
  sum-of-squares over grads and weights (a single NaN/inf poisons the
  sum, so per-bucket non-finite flags are DERIVED from sum finiteness
  with no separate count pass; exact element counts and abs-max come
  from the per-layer pass below) plus the loss's non-finite count,
  sum, and abs-max. Buckets reuse ``parallel/overlap.bucket_plan``
  (dtype-homogeneous, size-capped segments), so the whole summary is
  a handful of fused sum reductions.
  ``MXTPU_HEALTH`` and ``MXTPU_HEALTH_ACTION`` are compile-signature
  tokens (``ndarray/register.py``): toggling retraces cleanly instead
  of replaying the other graph. Observability must not perturb what it
  observes: the sentinels only ADD outputs — with the faultpoint
  disarmed, training with ``MXTPU_HEALTH=1`` is bitwise-identical to
  ``MXTPU_HEALTH=0`` (pinned by test).

- **Per-layer statistics + the revived Monitor**: every
  ``MXTPU_HEALTH_INTERVAL`` steps (and whenever an attached
  ``Monitor`` is activated, or on the first anomaly of an episode) a
  full per-layer pass computes per-parameter weight/grad
  nonfinite/abs-max/L2 rows from the arrays the fused program already
  produced — one batched host transfer, never per step.
  ``Monitor.install()`` on a hybridized block registers the monitor
  here (:func:`attach_monitor`); rows are delivered through the
  monitor's own ``stat_func`` under the reference's ``(batch, name,
  stat)`` row contract, replacing the dead Python forward hooks.

- **Cross-rank SDC digests**: each checked step folds the per-bucket
  summary into a CRC32 checksum; the kvstore heartbeat carries
  ``(seq, checksum)`` (:func:`shared_digest` — published only for
  mesh-DP programs whose grads are bitwise-shared) to the PS server (the
  length-gated v1-payload idiom) and ``metrics()['kvstore_server']``
  leave-one-out-compares same-seq checksums: under DP replication the
  reduced update is bitwise-shared, so a rank whose post-reduction
  checksum disagrees is flagged ``sdc_suspect.<r>``.

- **Anomaly response** (:func:`note_step`): a non-finite sentinel or a
  loss spike past ``MXTPU_HEALTH_LOSS_FACTOR`` x the rolling-median
  loss (the watchdog envelope math) trips ONE ``numerics``
  flight-record dump per episode — bundling the offending
  bucket→param names, the per-layer stats and the last-K loss window —
  and applies ``MXTPU_HEALTH_ACTION``:

  ========== ======================================================
  ``record``    dump + counters only (default)
  ``skip_step`` the poisoned update is DISCARDED — the fused program
                selects the old weights/optimizer state in-graph
                (donation-safe), the host rolls the update-count
                bookkeeping back and skips the aux adoption, so the
                step bitwise never happened (counted,
                goodput-annotated via ``note_event``)
  ``halt``      the in-graph select also protects the weights, then
                :class:`HealthHaltError` raises out of the step
  ========== ======================================================

  The in-graph select covers non-finite sentinels only: a finite loss
  spike is detected after the donated buffers are already committed,
  so spikes are record-only under every action.

Chaos: the ``health.grad.corrupt`` faultpoint injects gradient
corruption in-graph via a traced operand (:func:`corruption_operand`,
applied by :func:`apply_corruption` as an exact multiply-by-one
identity on clean steps). The configured exception type picks the
corruption: ``raise:ArithmeticError`` → NaN, ``raise:OverflowError`` →
inf, any other raise → a finite exponent bit-flip (grads doubled — the
pure-SDC shape only the cross-rank digest can catch).

Surfaces: ``profiler.metrics()['health']`` (registered provider,
counted with profiling off), a ``dumps()`` line, ``mxtpu_health_*``
on ``/metrics``, ``health:*`` markers in the ``health`` trace lane,
and the ``numerics`` flight-record dumps. Env knobs
(docs/ENV_VARS.md): ``MXTPU_HEALTH``, ``MXTPU_HEALTH_ACTION``,
``MXTPU_HEALTH_INTERVAL``, ``MXTPU_HEALTH_LOSS_FACTOR``,
``MXTPU_HEALTH_WINDOW``.
"""
from __future__ import annotations

import collections
import functools as _functools
import math
import statistics
import weakref
import zlib

from . import faultpoint as _faultpoint
from . import flightrec as _flightrec
from . import goodput as _goodput
from . import locktrace as _locktrace
from .watchdog import _envf
from ..base import getenv as _getenv

__all__ = [
    "HealthHaltError", "enabled", "action", "configure", "reset",
    "graph_summary", "apply_corruption", "corruption_operand",
    "note_step", "note_amp", "attach_monitor", "detach_monitor",
    "last_digest", "shared_digest", "layer_stats", "last_layer_stats",
    "stats",
]

ACTIONS = ("record", "skip_step", "halt")


class HealthHaltError(RuntimeError):
    """Raised out of the fused step when a non-finite sentinel fires
    under ``MXTPU_HEALTH_ACTION=halt``. The step raises only AFTER the
    in-graph-selected clean weights/optimizer state were adopted back
    into the parameters and the update-count bookkeeping was rolled
    back (adopt-then-raise is load-bearing under donation: the
    program's INPUT buffers are already deleted on TPU, so skipping
    adoption would leave every parameter on a dead buffer) — a caller
    that catches this can checkpoint-and-exit cleanly."""


def enabled():
    """Whether the in-graph sentinels are on. Read from the env PER
    STEP (never per op) so the value can never diverge from the
    ``MXTPU_HEALTH`` compile-signature token that keys the fused-step
    cache — one source of truth for both the host gate and the
    retrace."""
    return _getenv("MXTPU_HEALTH", "0") not in ("", "0", "false", "off")


def action():
    """The anomaly response policy (``MXTPU_HEALTH_ACTION``); unknown
    values degrade to ``record`` (observability must not crash the
    step it observes). Env-read per step for the same one-source-of-
    truth reason as :func:`enabled` — the value changes the traced
    update graph (the skip select), so it is a signature token."""
    act = _getenv("MXTPU_HEALTH_ACTION", "record") or "record"
    return act if act in ACTIONS else "record"


_lock = _locktrace.named_lock("healthmon.state")
_cfg = {}


def _defaults():
    return {
        # full per-layer pass cadence (0 = only when a Monitor asks or
        # an anomaly dump needs the rows)
        "interval": int(_envf("MXTPU_HEALTH_INTERVAL", 0)),
        # loss-spike envelope: factor x rolling median (0 = off)
        "loss_factor": _envf("MXTPU_HEALTH_LOSS_FACTOR", 8.0),
        "window": int(_envf("MXTPU_HEALTH_WINDOW", 32)),
        "min_samples": 3,  # spike check arms like the watchdog median
    }


_cfg.update(_defaults())

# mxlint: disable=MX003 (every mutation below sits under the healthmon.state named lock; the waiver covers the definition lines the rule anchors to)
_stats = {
    "steps": 0,            # fused steps the sentinels checked
    "anomalies": 0,        # steps with any anomaly (nonfinite or spike)
    "nonfinite_steps": 0,
    "loss_spikes": 0,
    "skipped_steps": 0,    # updates discarded under action=skip_step
    "halts": 0,
    "dumps": 0,            # numerics flight-record shards written
    "episodes": 0,         # anomaly episodes (latch: one dump each)
    "layer_passes": 0,     # full per-layer stat passes
    "monitor_rows": 0,     # rows delivered to attached Monitors
    "last_anomaly_step": -1,
    "last_loss": 0.0,
    # AMP loss-scaler accounting (single owner, ISSUE 15 satellite):
    # fed by contrib/amp/loss_scaler.py with or without profiling
    "amp_overflow_skips": 0,
    "amp_scale_updates": 0,
    "amp_loss_scale": 0.0,
}
_losses = collections.deque(maxlen=max(1, _cfg["window"]))
_state = {"episode": False, "digest": None,
          "digest_shared": False, "layer_rows": None}
_monitors = []  # weakrefs to attached Monitor instances


def configure(interval=None, loss_factor=None, window=None,
              min_samples=None):
    """Override the env-derived host knobs at runtime (tests,
    notebooks). The graph-shaping switches (``MXTPU_HEALTH`` /
    ``MXTPU_HEALTH_ACTION``) are deliberately NOT settable here — they
    are compile-signature tokens and must change through the env so
    the fused-step cache retraces."""
    global _losses
    with _lock:
        if interval is not None:
            _cfg["interval"] = int(interval)
        if loss_factor is not None:
            _cfg["loss_factor"] = float(loss_factor)
        if min_samples is not None:
            _cfg["min_samples"] = int(min_samples)
        if window is not None:
            _cfg["window"] = int(window)
            _losses = collections.deque(_losses,
                                        maxlen=max(1, int(window)))


def reset():
    """Clear all counters/windows/latches and re-read the knobs from
    the env (test isolation). Attached monitors are dropped."""
    global _losses
    with _lock:
        _cfg.clear()
        _cfg.update(_defaults())
        _losses = collections.deque(maxlen=max(1, _cfg["window"]))
        for k in _stats:
            _stats[k] = -1 if k == "last_anomaly_step" else 0
        _stats["last_loss"] = _stats["amp_loss_scale"] = 0.0
        _state["episode"] = False
        _state["digest"] = None
        _state["digest_shared"] = False
        _state["layer_rows"] = None
        del _monitors[:]


def stats():
    """Flat JSON-safe snapshot — ``profiler.metrics()['health']``."""
    with _lock:
        out = dict(_stats)
        out["loss_median"] = round(statistics.median(_losses), 6) \
            if _losses else 0.0
        out["in_episode"] = int(_state["episode"])
        d = _state["digest"]
        if d is not None:
            out["digest_seq"], out["digest_checksum"] = d
        out["interval"] = _cfg["interval"]
        out["loss_factor"] = _cfg["loss_factor"]
    out["enabled"] = int(enabled())
    out["action"] = action()
    return out


def last_digest():
    """(seq, CRC32 checksum) of the newest checked step's per-bucket
    summary, or None — the local digest gauge
    (``metrics()['health']['digest_seq'/'digest_checksum']``)."""
    return _state["digest"]


def shared_digest():
    """The digest the kvstore heartbeat publishes for cross-rank SDC
    comparison, or None. Only digests from programs whose gradients
    are BITWISE-SHARED across ranks qualify (the mesh-DP fused step:
    grads psum'd in-graph before the summary) — publishing a
    single-device or host-reduced-DP digest would diverge on every
    healthy step and page operators with false SDC. The fused step
    marks eligibility per compiled program (``hmeta['replicated']``)."""
    return _state["digest"] if _state["digest_shared"] else None


def last_layer_stats():
    """The newest full per-layer pass's rows
    (``[(name, {w_/g_ nonfinite/absmax/l2}), ...]``), or None."""
    return _state["layer_rows"]


# -- monitors ----------------------------------------------------------------

def attach_monitor(mon, params=None):
    """Route per-layer rows from the fused step's health outputs into
    ``mon`` (a ``mxnet_tpu.monitor.Monitor``) — the hybridized-block
    replacement for the Python forward hooks the cached program
    bypasses. ``params`` (an iterable of parameter NAMES —
    ``Monitor.install`` passes the installed block's) scopes delivery:
    a monitor only receives rows for its own block's parameters, and
    only a monitor that actually received rows has its eager
    ``toc()`` sweep suppressed — two monitors on two nets in one
    process never cross-talk. ``None`` = receive every trainer's rows.
    Held weakly; detach is automatic on collection."""
    scope = frozenset(params) if params is not None else None
    with _lock:
        for i, (r, s) in enumerate(_monitors):
            if r() is mon:
                # one monitor installed on several blocks: scopes union
                _monitors[i] = (r, None if scope is None or s is None
                                else s | scope)
                return
        _monitors[:] = [(r, s) for r, s in _monitors
                        if r() is not None]
        _monitors.append((weakref.ref(mon), scope))


def detach_monitor(mon):
    with _lock:
        _monitors[:] = [(r, s) for r, s in _monitors
                        if r() is not None and r() is not mon]


def _live_monitors():
    with _lock:
        refs = list(_monitors)
    return [(m, s) for m, s in ((r(), s) for r, s in refs)
            if m is not None]


# -- the traced half ---------------------------------------------------------
# Pure functions over operands: no env, no clocks, no host RNG — they
# run INSIDE the donated fused-step program.

def graph_summary(plan, grads, weights, loss, axis_name=None):
    """Build the in-graph sentinel summary: per-bucket L2
    sum-of-squares over ``grads`` and ``weights`` plus the loss
    vector's non-finite count / sum / abs-max. ``plan`` is an
    ``overlap.bucket_plan`` index grouping (dtype-homogeneous
    segments), so the whole summary is a handful of fused reductions.

    Price engineering (``BENCH_MODEL=health_overhead`` keeps this
    honest): the per-step sentinel is SUM reductions only — one
    ``sum(x*x)`` per leaf, folded per bucket. A single NaN/inf poisons
    the sum, so non-finiteness needs no separate ``isfinite`` count
    pass (a per-element count + abs-max pass measured 4-8x the whole
    sentinel budget on CPU; exact counts and abs-max live in the
    per-layer pass, which runs on interval/anomaly only). ``weights``
    should be the PRE-update weights — their reductions overlap the
    whole program instead of extending the update's critical path; a
    poisoned UPDATE is still caught in the same step through the
    grads, and a sumsq overflow (exploding but technically finite
    values) flags too, which is exactly the right bias.

    Returns ``(packed, ok)``: ``packed`` is ONE f32 vector of length
    ``2 * n_buckets + 3`` — ``[g_sumsq..., w_sumsq..., loss_bad,
    loss_sum, loss_absmax]`` (one output, ONE host transfer per step;
    a dict of small leaves measured as one dispatch per leaf) — and
    ``ok`` is the scalar all-finite flag the in-graph skip select keys
    on (consumed inside the program, never transferred).
    :func:`unpack_summary` restores the named dict host-side, with the
    per-bucket ``g_bad``/``w_bad`` indicators derived there.
    ``axis_name`` (mesh mode) psum/pmax-folds the per-shard loss stats
    so every replica sees the global values."""
    import jax.numpy as jnp
    from jax import lax

    def _bucket_sumsq(arrs):
        ssq = []
        for bucket in plan:
            b = [jnp.sum(jnp.square(arrs[i].astype(jnp.float32)))
                 for i in bucket]
            ssq.append(_functools.reduce(lambda x, y: x + y, b))
        return jnp.stack(ssq)

    g_sumsq = _bucket_sumsq(list(grads))
    w_sumsq = _bucket_sumsq(list(weights))
    lf32 = jnp.ravel(loss).astype(jnp.float32)
    loss_bad = jnp.sum((~jnp.isfinite(lf32)).astype(jnp.int32))
    loss_sum = jnp.sum(lf32)
    loss_absmax = jnp.max(jnp.abs(lf32))
    if axis_name is not None:
        loss_bad = lax.psum(loss_bad, axis_name)
        loss_sum = lax.psum(loss_sum, axis_name)
        loss_absmax = lax.pmax(loss_absmax, axis_name)
    packed = jnp.concatenate([
        g_sumsq, w_sumsq,
        jnp.stack([loss_bad.astype(jnp.float32), loss_sum,
                   loss_absmax])])
    ok = jnp.all(jnp.isfinite(g_sumsq)) \
        & jnp.all(jnp.isfinite(w_sumsq)) & (loss_bad == 0)
    return packed, ok


def unpack_summary(packed, n_buckets):
    """Host half of the packed summary wire format (see
    :func:`graph_summary`): a numpy view of the packed vector back
    into the named dict, with the per-bucket poisoned indicators
    derived from sum finiteness."""
    import numpy as np
    v = np.asarray(packed)
    g_sumsq = v[:n_buckets]
    w_sumsq = v[n_buckets:2 * n_buckets]
    out = {
        "g_sumsq": g_sumsq, "w_sumsq": w_sumsq,
        "g_bad": (~np.isfinite(g_sumsq)).astype(np.int32),
        "w_bad": (~np.isfinite(w_sumsq)).astype(np.int32),
        "loss_bad": int(v[2 * n_buckets]),
        "loss_sum": float(v[2 * n_buckets + 1]),
        "loss_absmax": float(v[2 * n_buckets + 2]),
    }
    out["ok"] = bool(out["g_bad"].sum() == 0
                     and out["w_bad"].sum() == 0
                     and out["loss_bad"] == 0)
    return out


def apply_corruption(grads, corrupt):
    """Scale the first gradient leaf by ``1 + corrupt`` — an EXACT
    identity at ``corrupt == 0.0`` (x * 1.0 is bitwise x for every
    float, sign of zero included), NaN/inf poison or a finite exponent
    flip when the ``health.grad.corrupt`` faultpoint armed the
    operand. Placed after the (mesh-mode) gradient reduction, so the
    injected corruption models a rank corrupting its OWN copy of the
    bitwise-shared reduced update — the SDC shape the cross-rank
    digest comparison exists to catch."""
    grads = list(grads)
    grads[0] = grads[0] * (1.0 + corrupt).astype(grads[0].dtype)
    return tuple(grads)


def corruption_operand():
    """Host half of the chaos seam: consult the ``health.grad.corrupt``
    faultpoint and return the corruption scalar threaded into the
    program (0.0 = clean). The configured exception type picks the
    corruption: OverflowError → inf, any other ArithmeticError → NaN,
    any other Exception → 1.0 (grads doubled — finite SDC)."""
    if not _faultpoint.ACTIVE:
        return 0.0
    try:
        _faultpoint.check("health.grad.corrupt")
    except OverflowError:
        return float("inf")
    except ArithmeticError:
        return float("nan")
    except Exception:
        return 1.0
    return 0.0


# -- the host half -----------------------------------------------------------

def layer_stats(names, grads, weights):
    """Full per-layer pass: one batched host transfer of every grad and
    weight, then per-parameter nonfinite/abs-max/L2 rows. Interval/
    anomaly/Monitor path only — never per step."""
    import numpy as np
    import jax
    host = jax.device_get((list(grads), list(weights)))

    def _one(a):
        a = np.asarray(a)
        a64 = a.astype(np.float64)
        return (int((~np.isfinite(a)).sum()),
                float(np.max(np.abs(a64))) if a.size else 0.0,
                float(np.sqrt(np.square(a64).sum())))

    rows = []
    for name, g, w in zip(names, host[0], host[1]):
        g_bad, g_absmax, g_l2 = _one(g)
        w_bad, w_absmax, w_l2 = _one(w)
        rows.append((name, {
            "g_nonfinite": g_bad, "g_absmax": g_absmax, "g_l2": g_l2,
            "w_nonfinite": w_bad, "w_absmax": w_absmax, "w_l2": w_l2,
        }))
    return rows


def _deliver_monitor_rows(mons, names, grads, weights):
    """Feed activated attached Monitors the per-layer rows through
    their OWN ``stat_func`` — the reference ``(batch, name, stat)`` row
    contract, weight then ``<name>_grad``, in parameter order (what the
    eager ``toc()`` sweep produces). Each monitor receives only the
    rows inside its attach scope, and only monitors that actually got
    rows are marked so ``toc()`` skips their collect_params pass for
    this batch (no duplicates, no cross-talk between blocks)."""
    active = [(m, s) for m, s in mons if getattr(m, "activated", False)]
    if not active:
        return 0
    from ..ndarray import NDArray
    delivered = 0
    got = set()
    for name, g, w in zip(names, grads, weights):
        gname = name + "_grad"
        takers = [m for m, s in active if s is None or name in s]
        if not takers:
            continue
        wnd = gnd = None
        for m in takers:
            # honor the monitor's own name filter here, so the
            # delivered count (and the toc-suppression mark) reflect
            # rows that actually enqueued — a pattern matching nothing
            # leaves the monitor to its eager sweep
            sent = 0
            if m.re_prog.match(name):
                wnd = NDArray(w) if wnd is None else wnd
                m.stat_helper_always(name, wnd)
                sent += 1
            if m.re_prog.match(gname):
                gnd = NDArray(g) if gnd is None else gnd
                m.stat_helper_always(gname, gnd)
                sent += 1
            if sent:
                delivered += sent
                got.add(id(m))
    for m, _s in active:
        if id(m) in got:
            m._fused_batch = m.step
    return delivered


def note_step(summary, hmeta, grads, weights, batch_size):
    """The per-step host half, called by the fused step after the
    program ran and BEFORE result adoption. Fetches the tiny summary
    (the only per-step device sync the plane costs —
    ``BENCH_MODEL=health_overhead`` prices it under 0.5% of a fused
    step), updates the digest/loss window, runs the interval/Monitor
    per-layer pass, and applies the anomaly response. Returns
    ``{"anomaly": bool, "skipped": bool, "halt": exc-or-None}``:
    under ``action=halt`` the error is RETURNED, not raised — the
    caller must adopt the in-graph-selected clean outputs and roll the
    update counts back BEFORE raising it (see
    :class:`HealthHaltError` for why that ordering is load-bearing
    under donation)."""
    import numpy as np
    import jax
    packed = np.asarray(jax.device_get(summary), np.float32)
    host = unpack_summary(packed, len(hmeta["plan"]))
    g_bad = host["g_bad"]
    w_bad = host["w_bad"]
    loss_bad = host["loss_bad"]
    # poisoned buckets (indicators) + poisoned loss elements
    nonfinite = int(g_bad.sum()) + int(w_bad.sum()) + loss_bad
    loss_mean = host["loss_sum"] / max(int(batch_size), 1)
    checksum = zlib.crc32(packed.tobytes())
    act = hmeta["action"]
    spike = False
    with _lock:
        _stats["steps"] += 1
        seq = _stats["steps"]
        _state["digest"] = (seq, int(checksum))
        _state["digest_shared"] = bool(hmeta.get("replicated"))
        finite_loss = loss_bad == 0 and math.isfinite(loss_mean)
        if finite_loss:
            _stats["last_loss"] = round(loss_mean, 6)
        factor = _cfg["loss_factor"]
        if finite_loss and factor > 0 \
                and len(_losses) >= _cfg["min_samples"]:
            med = statistics.median(_losses)
            if med > 0 and loss_mean > factor * med:
                spike = True
        anomaly = nonfinite > 0 or spike
        if anomaly:
            _stats["anomalies"] += 1
            _stats["last_anomaly_step"] = seq
            if nonfinite:
                _stats["nonfinite_steps"] += 1
            if spike:
                _stats["loss_spikes"] += 1
        elif finite_loss:
            # anomalous losses stay out of the window: a spike must not
            # drag the median up toward itself (the leave-one-out
            # spirit of the straggler baseline)
            _losses.append(loss_mean)
        skipped = bool(nonfinite and act == "skip_step")
        if skipped:
            _stats["skipped_steps"] += 1
        if nonfinite and act == "halt":
            _stats["halts"] += 1
        first_in_episode = anomaly and not _state["episode"]
        if anomaly and first_in_episode:
            _stats["episodes"] += 1
        _state["episode"] = anomaly
        interval = _cfg["interval"]
        loss_window = list(_losses)
    # everything below runs OUTSIDE the state lock (flightrec/profiler
    # take their own locks — the watchdog trip discipline)
    mons = _live_monitors()
    name_set = set(hmeta["names"])
    mons = [(m, s) for m, s in mons if s is None or s & name_set]
    want_layers = (interval > 0 and seq % interval == 0) \
        or any(getattr(m, "activated", False) for m, _s in mons) \
        or first_in_episode
    rows = None
    if want_layers:
        rows = layer_stats(hmeta["names"], grads, weights)
        delivered = _deliver_monitor_rows(mons, hmeta["names"], grads,
                                          weights)
        with _lock:
            _stats["layer_passes"] += 1
            _stats["monitor_rows"] += delivered
            _state["layer_rows"] = rows
    if not anomaly:
        return {"anomaly": False, "skipped": False, "halt": None}

    reason = "nonfinite" if nonfinite else "loss_spike"
    offending = []
    for b in range(len(g_bad)):
        if int(g_bad[b]) or int(w_bad[b]):
            offending.append({
                "bucket": b,
                "params": hmeta["bucket_names"][b],
                "grad_poisoned": int(g_bad[b]),
                "weight_poisoned": int(w_bad[b]),
                "grad_sumsq": float(host["g_sumsq"][b]),
            })
    from .. import profiler as _profiler
    _profiler.marker("health:%s" % reason, lane="health",
                     category="health",
                     args={"step": seq, "nonfinite": nonfinite,
                           "loss": loss_mean, "action": act,
                           "skipped": skipped})
    if first_in_episode:
        # ONE flight-record dump per episode: a NaN that persists for
        # 500 steps is one readable post-mortem, not a dump storm; the
        # latch re-arms on the first clean step
        path = _flightrec.dump(
            "numerics",
            extra={
                "step": seq, "reason": reason, "action": act,
                "skipped": skipped,
                "suspect_rank": _profiler.PID,
                "nonfinite": nonfinite, "loss_bad": loss_bad,
                "loss_mean": loss_mean,
                "loss_window": loss_window,
                "offending_buckets": offending,
                "layer_stats": [
                    {"name": n, **st} for n, st in (rows or [])],
            },
            swallow=True)
        if path is not None:
            with _lock:
                _stats["dumps"] += 1
    if skipped and _goodput.OPEN:
        # the discarded update is badput the run ledger must name: the
        # step's wall time stays in compute (the work WAS done), the
        # event row says the result was thrown away
        _goodput.note_event("health_skip_step", step=seq, reason=reason)
    halt = None
    if nonfinite and act == "halt":
        halt = HealthHaltError(
            "non-finite training step %d (poisoned buckets %s): "
            "MXTPU_HEALTH_ACTION=halt" % (
                seq, [o["bucket"] for o in offending] or ["loss"]))
    return {"anomaly": True, "skipped": skipped, "halt": halt}


def note_amp(overflow, loss_scale):
    """AMP loss-scaler accounting (fed by
    ``contrib/amp/loss_scaler.py`` — ``metrics()['health']`` is the
    single owner of overflow/skip counts, with or without profiling,
    the ``account`` contract)."""
    with _lock:
        _stats["amp_scale_updates"] += 1
        _stats["amp_loss_scale"] = float(loss_scale)
        if overflow:
            _stats["amp_overflow_skips"] += 1


# surfaces as metrics()['health'] and a dumps() provider line
# (healthmon is imported by gluon/fused_step and kvstore_async, after
# the profiler module is fully loaded — no cycle)
from .. import profiler as _profiler  # noqa: E402

_profiler.register_stats_provider("health", stats)
