"""Fault-injection points (``MXNET_FAULTPOINTS=...``) — chaos testing
for the framework's degradation paths.

The stack promises "never a crash" in many places — eager fallback in
the imperative jit and the fused train step, bulk-segment eager replay,
kvstore reconnect/retry, prefetch error propagation, atomic checkpoint
writes — but a promise only tested on the happy path is aspirational
(the reference has only ``GetDeadNodes``-style heartbeat detection,
ref: src/kvstore/kvstore_dist.h:121, and no systematic fault testing;
the dependency engine's contract is that async failures surface at
``WaitForVar``/``WaitForAll``, SURVEY §3). This module makes failure
semantics *provable*: named fault points are woven into the framework's
failure seams, and ``tests/test_faultpoints.py`` drives them under
seeded schedules asserting no hang, no silent corruption, and full
accounting.

Fault-point catalog (where each fires — docs/RESILIENCE.md has the
failure → behavior → counter table):

==========================  ================================================
``kvstore.connect``         ``AsyncPSClient`` socket connect (per attempt)
``kvstore.send``            ``AsyncPSClient._call`` transport (per attempt)
``kvstore.pull``            ``AsyncPSClient.pull`` transport (per attempt)
``io.prefetch.place``       ``DevicePrefetchIter`` worker, before place_fn
``engine.bulk.compile``     bulk-segment runner compile (register.py)
``imperative.jit.compile``  dispatch-cache compile (register.py)
``fused_step.trace``        ``FusedTrainStep._build`` trace entry
``checkpoint.save``         ``base.atomic_write``, after the temp write,
                            before the atomic rename (mid-save crash)
``checkpoint.persist``      ``CheckpointManager._persist_bg``, after the
                            snapshot is taken, before the durable write
                            starts (the async snapshot→persist gap: a
                            death here loses exactly the one
                            unpublished step)
``storage.alloc``           creation-factory device placement
                            (``nd._ctx_place``)
``collective.allreduce``    gradient-reduction launch seams: the host
                            kvstore reducer (``parallel/elastic.py``
                            ``HostGradReducer``) per call, and
                            ``parallel/collectives.py`` helpers at
                            trace/launch time
``elastic.restore``         ``CheckpointManager.restore`` entry, before
                            any checkpoint bytes are read
``elastic.reshard``         ``ElasticController.reshard`` entry, before
                            the surviving world is committed
``io.shard.read``           ``RecordIORangeReader`` range-fetch, per
                            attempt (retried under ``_retry``)
``io.record.corrupt``       ``RecordIORangeReader`` record validation —
                            an injected raise is treated as a corrupt
                            record (skip-and-count under the budget)
``io.worker.decode``        ``DecodePool`` worker, before ``decode_fn``
                            (a raise is a worker death; the pool
                            restarts it under its per-worker budget)
``io.service.fetch``        ``ShardService.fetch_batch`` entry — the
                            disaggregated-service RPC seam
``health.grad.corrupt``     fused-step gradient corruption
                            (``_debug/healthmon.corruption_operand``):
                            the configured exception type picks the
                            in-graph poison — ``raise:OverflowError``
                            → inf, any other ``ArithmeticError`` →
                            NaN, any other raise → a finite exponent
                            bit-flip (grads doubled, the pure-SDC
                            shape only the cross-rank digest catches)
``net.partition``           kvstore frame send/recv seam
                            (``kvstore_async._send_frame`` /
                            ``_recv_frame``): a ``raise:
                            ConnectionError`` models the link going
                            down mid-frame; ``@skip``/``@p`` shape
                            asymmetric partitions
``net.delay``               same seam, ``delay:<t>`` — a slow or
                            congested link, per frame
``net.drop``                send seam only: a trigger (any action)
                            silently swallows the frame — it is sent
                            locally but never arrives, so the caller
                            blocks in recv until
                            ``MXTPU_PS_RECV_TIMEOUT`` surfaces it
``net.half_open``           recv seam only, ``delay:<silence>`` — the
                            peer holds the connection open but never
                            answers for ``<silence>`` seconds; with a
                            recv timeout configured the seam then
                            raises the same ``socket.timeout`` a real
                            silent peer produces
==========================  ================================================

Configuration — env var (parsed at import) or programmatic::

    MXNET_FAULTPOINTS="kvstore.send=raise:ConnectionError@p=0.3;\
io.prefetch.place=delay:50ms@n=3"
    MXNET_FAULTPOINTS_SEED=7   # default 0

    faultpoint.configure(
        "kvstore.send=raise:ConnectionError@p=0.3", seed=7)
    faultpoint.configure({"fused_step.trace": "raise:RuntimeError@n=1"})
    faultpoint.reset()

Spec grammar: ``point=action[@mod]...`` joined with ``;``. Actions:
``raise:ExcName`` (a builtin Exception subclass) and ``delay:50ms`` /
``delay:0.2s`` / ``delay:0.05``. Modifiers: ``p=<0..1>`` trigger
probability, ``n=<int>`` max triggers (then the point goes quiet),
``skip=<int>`` hits to let pass before arming.

Every chaos run is **deterministic and replayable**: each point draws
from its own ``random.Random`` seeded with ``(seed, point name)``, so a
point's trigger pattern depends only on the seed and its own hit
sequence, not on cross-point interleaving.

Zero overhead when inactive: instrumented sites guard with the inlined
``if _faultpoint.ACTIVE:`` module-bool test — the same idiom as the
profiler hooks' ``_HOOKS and _ACTIVE`` guard (mxlint MX002's spirit;
``BENCH_MODEL=profiler_overhead`` keeps the dispatch path honest).

Observability: per-point trigger counters surface as
``profiler.metrics()['faults']`` (registered stats provider — counted
even while no profile run is active) and each trigger emits a
``fault:<point>`` instant marker into the trace when profiling is on.
"""
from __future__ import annotations

import builtins
import os
import random
import time

from . import locktrace as _locktrace
from ..base import getenv as _getenv

__all__ = [
    "ACTIVE", "POINTS", "configure", "reset", "check", "is_active",
    "metrics", "reset_counters", "triggers",
]

# Module-level gate, read inline by the instrumented sites
# (`if _faultpoint.ACTIVE: _faultpoint.check(name)`) so the unconfigured
# cost is one attribute load + truth test.
ACTIVE = False

# The woven seams. configure() validates names against this catalog so a
# typo'd spec fails loudly instead of silently injecting nothing.
POINTS = frozenset((
    "kvstore.connect",
    "kvstore.send",
    "kvstore.pull",
    "io.prefetch.place",
    "engine.bulk.compile",
    "imperative.jit.compile",
    "fused_step.trace",
    "checkpoint.save",
    "checkpoint.persist",
    "storage.alloc",
    "collective.allreduce",
    "elastic.restore",
    "elastic.reshard",
    "io.shard.read",
    "io.record.corrupt",
    "io.worker.decode",
    "io.service.fetch",
    "health.grad.corrupt",
    # on-the-wire network chaos (kvstore_async frame send/recv seam)
    "net.partition",
    "net.delay",
    "net.drop",
    "net.half_open",
))

_lock = _locktrace.named_lock("faultpoint.config")
_rules = {}     # point name -> _Rule
_counters = {}  # point name -> times a fault actually triggered


class _Rule:
    """One configured fault: action + arming state + per-point RNG."""

    __slots__ = ("name", "action", "exc_type", "delay_s", "p",
                 "remaining", "skip", "rng", "spec")

    def __init__(self, name, action, exc_type, delay_s, p, n, skip, seed,
                 spec):
        self.name = name
        self.action = action        # "raise" | "delay"
        self.exc_type = exc_type    # Exception subclass for "raise"
        self.delay_s = delay_s      # seconds for "delay"
        self.p = p                  # trigger probability per armed hit
        self.remaining = n          # triggers left (None = unlimited)
        self.skip = skip            # hits to let pass before arming
        # (seed, name)-derived stream: a point's schedule is a pure
        # function of the seed and its own hit sequence — replayable
        # regardless of how other points interleave
        self.rng = random.Random("%s:%s" % (seed, name))
        self.spec = spec            # original text, for reporting


def _resolve_exception(name):
    exc = getattr(builtins, name, None)
    if not (isinstance(exc, type) and issubclass(exc, Exception)):
        raise ValueError(
            "faultpoint raise action needs a builtin Exception subclass, "
            "got %r" % (name,))
    return exc


def _parse_delay(arg):
    if arg.endswith("ms"):
        return float(arg[:-2]) / 1000.0
    if arg.endswith("s"):
        return float(arg[:-1])
    return float(arg)


def _parse_one(name, spec, seed):
    """``action[:arg][@k=v]...`` -> _Rule for ``name``."""
    if name not in POINTS:
        raise ValueError(
            "unknown fault point %r; known points: %s"
            % (name, ", ".join(sorted(POINTS))))
    head, *mods = spec.split("@")
    action, _, arg = head.partition(":")
    action = action.strip()
    exc_type, delay_s = None, 0.0
    if action == "raise":
        exc_type = _resolve_exception(arg.strip() or "RuntimeError")
    elif action == "delay":
        delay_s = _parse_delay(arg.strip() or "0.05")
        if delay_s < 0:
            raise ValueError("faultpoint delay must be >= 0, got %r"
                             % (arg,))
    else:
        raise ValueError(
            "unknown faultpoint action %r (want raise:Exc or delay:50ms)"
            % (action,))
    p, n, skip = 1.0, None, 0
    for mod in mods:
        k, _, v = mod.partition("=")
        k = k.strip()
        if k == "p":
            p = float(v)
            if not 0.0 <= p <= 1.0:
                raise ValueError("faultpoint p must be in [0, 1], got %r"
                                 % (v,))
        elif k == "n":
            n = int(v)
            if n < 0:
                raise ValueError("faultpoint n must be >= 0, got %r"
                                 % (v,))
        elif k == "skip":
            skip = int(v)
            if skip < 0:
                raise ValueError("faultpoint skip must be >= 0, got %r"
                                 % (v,))
        else:
            raise ValueError("unknown faultpoint modifier %r "
                             "(want p=/n=/skip=)" % (k,))
    return _Rule(name, action, exc_type, delay_s, p, n, skip, seed, spec)


def parse(spec, seed=0):
    """Parse a full ``MXNET_FAULTPOINTS`` string (or dict of
    point -> action spec) into {name: _Rule} without installing it."""
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = []
        for part in str(spec).split(";"):
            part = part.strip()
            if not part:
                continue
            name, eq, body = part.partition("=")
            if not eq:
                raise ValueError(
                    "bad faultpoint spec %r (want point=action)" % (part,))
            items.append((name.strip(), body.strip()))
    return {name: _parse_one(name, body, seed) for name, body in items}


def configure(spec, seed=None):
    """Install a fault schedule, REPLACING any previous one (so a run's
    behavior is a pure function of this call). ``spec`` is the env-string
    grammar or a dict of ``point -> "action[@mods]"``. ``seed`` defaults
    to ``MXNET_FAULTPOINTS_SEED`` (0 when unset). Returns the installed
    point names."""
    global ACTIVE
    if seed is None:
        seed = int(_getenv("MXNET_FAULTPOINTS_SEED", "0"))
    rules = parse(spec, seed)
    with _lock:
        _rules.clear()
        _rules.update(rules)
        _counters.clear()  # a new schedule starts its accounting at zero
        ACTIVE = bool(_rules)
    return sorted(rules)


def reset():
    """Remove every configured fault and clear the trigger counters
    (test isolation). The instrumented sites go back to the single
    guarded-branch cost."""
    global ACTIVE
    with _lock:
        _rules.clear()
        _counters.clear()
        ACTIVE = False


def is_active():
    return ACTIVE


def triggers(name):
    """How many times the named point actually fired."""
    with _lock:
        return _counters.get(name, 0)


def metrics():
    """JSON-safe per-point trigger counts — the ``faults`` section of
    ``profiler.metrics()`` (registered as a stats provider; counted with
    or without an active profile run)."""
    with _lock:
        return dict(_counters)


def reset_counters():
    with _lock:
        _counters.clear()


def check(name):
    """The injection site. Callers guard with ``if _faultpoint.ACTIVE:``
    so the unconfigured cost stays off the hot path. Decides — under the
    point's seeded RNG — whether this hit triggers; a trigger counts,
    emits a trace marker, then sleeps (``delay``) or raises (``raise``)
    the configured exception out of the instrumented seam, exactly where
    a real failure would surface. Returns True when a non-raising
    trigger fired (after its sleep) and False otherwise, so seams with
    behavior beyond sleep-or-raise — the ``net.drop`` /
    ``net.half_open`` socket shim — can act on the trigger themselves."""
    with _lock:
        rule = _rules.get(name)
        if rule is None:
            return False
        if rule.skip > 0:
            rule.skip -= 1
            return False
        if rule.remaining is not None and rule.remaining <= 0:
            return False
        if rule.p < 1.0 and rule.rng.random() >= rule.p:
            return False
        if rule.remaining is not None:
            rule.remaining -= 1
        _counters[name] = _counters.get(name, 0) + 1
        action, exc_type, delay_s = rule.action, rule.exc_type, rule.delay_s
    _mark(name, action)
    if action == "delay":
        time.sleep(delay_s)
        return True
    raise exc_type("faultpoint %r injected %s" % (name, exc_type.__name__))


def _mark(name, action):
    """Instant marker in the trace so injected faults are visible next to
    the spans they perturb — and in the always-on flight-recorder ring,
    where a ``fault:*`` breadcrumb right before a crash dump is exactly
    the evidence a post-mortem wants. Lazy profiler import: profiler
    imports this package at module load (the stats-provider
    registration), so a top-level import here would be circular."""
    from . import flightrec as _flightrec
    if _flightrec.ENABLED:
        _flightrec.record_marker("fault:%s" % name, "fault",
                                 args={"action": action})
    from .. import profiler as _profiler
    if _profiler._ACTIVE:
        _profiler._emit("fault:%s" % name, "i", "fault",
                        args={"action": action})


def report():
    """Configured schedule + trigger counts (debugging aid)."""
    with _lock:
        return {
            "active": ACTIVE,
            "points": {n: r.spec for n, r in sorted(_rules.items())},
            "triggers": dict(_counters),
        }


# Env activation at import: the instrumented modules load after this one
# (profiler pulls in the _debug package before any subsystem), so an env
# schedule is live for the whole process without code changes.
_env_spec = _getenv("MXNET_FAULTPOINTS", "").strip()
if _env_spec:
    configure(_env_spec)
