"""Memory leak/growth watchdog + OOM post-mortem (ISSUE 13 tentpole c).

The step watchdog (``watchdog.py``) answers "why is nothing happening";
this module answers "why is memory gone". Two halves:

**Leak watchdog.** A daemon thread (lazily started by the first ledger
drain request, like the step watchdog's poller) samples the tagged
allocation ledger (``storage.ledger_metrics``) every
``MXTPU_MEMWATCH_POLL_S`` seconds into a rolling window. Post-warmup
(``MXTPU_MEMWATCH_WARMUP_S`` — compile/init churn is growth by
construction), a FULL window of monotone non-decreasing totals whose
net growth exceeds ``MXTPU_MEMWATCH_MIN_BYTES`` is a flagged leak:
counted, marked in the trace, and the flight recorder dumps ONE
post-mortem shard naming the top-K growing tags and the sampled
allocation sites — exactly once per episode (the latch re-arms only
after live bytes fall back below the level at trip). The profiler's
memory-sampler daemon also feeds the detector while profiling runs
(denser samples, same window).

**OOM post-mortem.** An XLA ``RESOURCE_EXHAUSTED`` today is an opaque
crash with no record of what was resident. Two chains into the same
dump: (a) handled allocation failures — the ``storage.alloc``
faultpoint path in ``nd._ctx_place`` — call :func:`oom_report` with the
failed request size before degrading; (b) unhandled OOMs reach the
flight recorder's ``sys.excepthook``, which asks :func:`is_oom` and
upgrades the dump trigger from ``exception`` to ``oom``. Either way the
shard bundles the full ledger (inside ``profiler.metrics()['memory']``),
the per-signature modeled peaks (``metrics()['compile']``), the failed
request size, and the top allocation sites — so an OOM names its cause.

Env knobs (docs/ENV_VARS.md): ``MXTPU_MEMWATCH`` (default 1),
``MXTPU_MEMWATCH_POLL_S`` (1.0), ``MXTPU_MEMWATCH_WINDOW`` (16),
``MXTPU_MEMWATCH_WARMUP_S`` (30), ``MXTPU_MEMWATCH_MIN_BYTES`` (64 MiB).
"""
from __future__ import annotations

import collections
import threading
import time

from . import flightrec as _flightrec
from . import locktrace as _locktrace
from .watchdog import _envf
from ..base import getenv as _getenv

__all__ = [
    "ENABLED", "configure", "reset", "observe", "stats", "ensure_thread",
    "is_oom", "oom_report", "check_now",
]


ENABLED = _getenv("MXTPU_MEMWATCH", "1") not in ("0", "false", "off")

_lock = _locktrace.named_lock("memwatch.state")
_cfg = {}


def _defaults():
    return {
        "poll_s": _envf("MXTPU_MEMWATCH_POLL_S", 1.0),
        "window": int(_envf("MXTPU_MEMWATCH_WINDOW", 16)),
        "warmup_s": _envf("MXTPU_MEMWATCH_WARMUP_S", 30.0),
        "min_bytes": int(_envf("MXTPU_MEMWATCH_MIN_BYTES", 64 << 20)),
    }


_cfg.update(_defaults())

_window = collections.deque(maxlen=max(2, _cfg["window"]))
_t0 = None           # first observe() — the warmup clock
_trip_level = None   # total bytes at the last trip; re-arm below it
_stats = {"samples": 0, "trips": 0, "dumps": 0, "oom_reports": 0,
          "last_trip_bytes": 0, "last_slope_bps": 0.0}
_thread = None
_stop = None
_reported_ooms = collections.deque(maxlen=8)  # id(exc) already dumped for  # mxlint: disable=MX003 (GIL-atomic deque on the rare OOM path)


def configure(poll_s=None, window=None, warmup_s=None, min_bytes=None,
              enabled=None):
    """Override the env-derived knobs at runtime (tests, notebooks)."""
    global ENABLED, _window
    with _lock:
        if poll_s is not None:
            _cfg["poll_s"] = float(poll_s)
        if warmup_s is not None:
            _cfg["warmup_s"] = float(warmup_s)
        if min_bytes is not None:
            _cfg["min_bytes"] = int(min_bytes)
        if window is not None:
            _cfg["window"] = int(window)
            _window = collections.deque(_window,
                                        maxlen=max(2, int(window)))
    if enabled is not None:
        ENABLED = bool(enabled)


def reset():
    """Stop the poller and clear all state; knobs re-read from the env
    (test isolation)."""
    global _t0, _trip_level, _thread, _stop, ENABLED, _window
    with _lock:
        stop, thread = _stop, _thread
        _thread = _stop = None
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5)
    with _lock:
        _cfg.clear()
        _cfg.update(_defaults())
        _window = collections.deque(maxlen=max(2, _cfg["window"]))
        _t0 = None
        _trip_level = None
        for k in _stats:
            _stats[k] = 0.0 if k == "last_slope_bps" else 0
    _reported_ooms.clear()
    ENABLED = _getenv("MXTPU_MEMWATCH", "1") not in ("0", "false", "off")


def stats():
    """Flat JSON-safe snapshot — surfaced as
    ``profiler.metrics()['memory']['memwatch']``."""
    with _lock:
        out = dict(_stats)
        out["enabled"] = int(ENABLED)
        out["window"] = len(_window)
        out["armed"] = int(_armed_locked(time.monotonic()))
    return out


def _armed_locked(now):
    return (_t0 is not None and now - _t0 >= _cfg["warmup_s"]
            and len(_window) == _window.maxlen
            and _trip_level is None)


def observe(snapshot=None, now=None):
    """Feed one ledger sample into the detector and trip it when the
    rolling window shows monotone post-warmup growth. Called by the
    daemon poll, by the profiler memory sampler while profiling runs,
    and synchronously by tests (``check_now``). Returns True on trip."""
    global _t0, _trip_level
    if not ENABLED:
        return False
    if snapshot is None:
        from .. import storage
        snapshot = storage.ledger_metrics()
    now = time.monotonic() if now is None else now
    total = int(snapshot.get("total_bytes", 0))
    with _lock:
        if _t0 is None:
            _t0 = now
        _stats["samples"] += 1
        if _trip_level is not None and \
                total < _trip_level - _cfg["min_bytes"] // 2:
            _trip_level = None  # episode over: growth receded, re-arm
        _window.append((now, total, dict(snapshot.get("by_tag", ()))))
        if not _armed_locked(now):
            return False
        pts = list(_window)
        grown = pts[-1][1] - pts[0][1]
        span = pts[-1][0] - pts[0][0]
        if grown < _cfg["min_bytes"] or span <= 0:
            return False
        if any(b[1] < a[1] for a, b in zip(pts, pts[1:])):
            return False  # not monotone: churn, not a leak
        slope = grown / span
        _trip_level = total
        _stats["trips"] += 1
        _stats["last_trip_bytes"] = total
        _stats["last_slope_bps"] = round(slope, 1)
        tag_growth = {
            t: pts[-1][2].get(t, 0) - pts[0][2].get(t, 0)
            for t in set(pts[0][2]) | set(pts[-1][2])}
        top_tags = sorted(((t, g) for t, g in tag_growth.items() if g > 0),
                          key=lambda kv: -kv[1])[:4]
    from .. import profiler as _profiler
    _profiler.marker(
        "memwatch:leak",
        args={"grown_bytes": grown, "window_s": round(span, 1),
              "slope_bps": round(slope, 1),
              "top_tags": dict(top_tags)},
        lane="memory", category="memwatch")
    path = _flightrec.dump(
        "memleak",
        extra={"grown_bytes": grown, "window_s": round(span, 1),
               "slope_bytes_per_s": round(slope, 1),
               "total_bytes": total,
               "top_tags": [{"tag": t, "grown_bytes": g}
                            for t, g in top_tags],
               "top_sites": snapshot.get("top_sites", [])},
        swallow=True)
    if path is not None:
        with _lock:
            _stats["dumps"] += 1
    return True


def check_now():
    """Force one detector pass synchronously (tests / debugger)."""
    return observe()


def _loop(stop):
    while not stop.wait(_cfg["poll_s"]):
        try:
            observe()
        except Exception:
            pass  # the watchdog must never take the process down


def ensure_thread():
    """Lazily start the daemon poller (idempotent) — called by the first
    ledger drain request so pure-eager processes get leak detection
    without any wiring."""
    global _thread, _stop
    if not ENABLED:
        return
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _stop = threading.Event()
        _thread = threading.Thread(target=_loop, args=(_stop,),
                                   daemon=True, name="mxtpu-memwatch")
        _thread.start()


# ---------------------------------------------------------------------------
# OOM post-mortem
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ")


def is_oom(exc):
    """Does this exception look like a device-memory exhaustion? XLA
    surfaces OOM as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...``; the
    match is textual on purpose (the exception type is version-dependent
    and the faultpoint path raises plain Exceptions)."""
    if exc is None:
        return False
    text = "%s: %s" % (type(exc).__name__, exc)
    return any(m in text for m in _OOM_MARKERS)


def oom_report(exc, requested_bytes=None, where=None):
    """Write the OOM post-mortem shard for a HANDLED allocation failure
    (the ``storage.alloc`` degradation path): the failed request size and
    site in ``trigger_info``, the full ledger + modeled peaks in the
    bundled ``metrics()``. Unhandled OOMs take the excepthook chain
    instead (``flightrec`` asks :func:`is_oom` there) — ``_reported_ooms``
    keeps the two from double-dumping one exception. Returns the shard
    path (None if swallowed/capped)."""
    key = id(exc)
    if key in _reported_ooms:
        return None
    _reported_ooms.append(key)
    with _lock:
        _stats["oom_reports"] += 1
    from .. import storage
    try:
        ledger = storage.ledger_metrics()
    except Exception:
        ledger = {}
    return _flightrec.dump(
        "oom",
        extra={"error": ("%s: %s" % (type(exc).__name__, exc))[:800],
               "requested_bytes": requested_bytes,
               "where": where,
               "ledger_total_bytes": ledger.get("total_bytes"),
               "ledger_by_tag": ledger.get("by_tag", {}),
               "top_sites": ledger.get("top_sites", [])},
        swallow=True)


def was_reported(exc):
    """Has ``oom_report`` already dumped for this exception object? (The
    excepthook consults this so a handled-then-reraised OOM yields ONE
    shard.)"""
    return id(exc) in _reported_ooms


# surfaces inside metrics()['memory'] via storage.memory_metrics();
# registered lazily there — no profiler import needed at module load
