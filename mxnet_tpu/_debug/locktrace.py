"""Runtime lock-order / race detector (``MXNET_DEBUG_LOCKS=1``).

The framework's invariants around its ~10 named locks and its daemon
threads (profiler continuous-dump + memory sampler, kvstore heartbeat
and server threads, io prefetch workers) are enforced here the way the
reference enforces memory errors with its sanitizer CI jobs
(ref: ci/docker/runtime_functions.sh sanitizer builds, tools/mxlint is
the static half): every framework lock is allocated through
``named_lock`` / ``named_condition``, and when tracing is enabled the
returned lock records

* the **acquisition-order graph** — a directed edge A -> B each time a
  thread acquires B while holding A. An edge pair (A -> B, B -> A) is a
  **lock-order inversion**: two threads interleaving those paths can
  deadlock. Names, not instances, define the order (the classic
  lock-hierarchy discipline), so two instances of the same subsystem
  lock share a node.
* **boundary violations** — locks held while crossing a jit-compile or
  device-sync boundary (``boundary()`` is called from the engine's
  wait points and the imperative dispatch cache's compile sites).
  Compiles and syncs can block for seconds; holding a framework lock
  across one starves every other thread that needs it, and holding the
  profiler event lock across a sync deadlocks against the daemon
  threads that emit events.

Findings surface in ``profiler.metrics()['locks']`` (the profiler asks
this module for ``report()`` when tracing is on) and via ``report()``
directly; ``tests/test_locktrace.py`` runs the concurrency-heavy suites
under the detector in tier-1 and asserts zero inversions.

When tracing is disabled (the default), ``named_lock`` still returns
the ``_NamedLock`` proxy — enabling at runtime (``enable()``) must
instrument locks created at import time — but its acquire/release are
a single module-bool test away from the raw ``threading.Lock``.
"""
from __future__ import annotations

import os
import threading
import traceback
from ..base import getenv as _getenv

__all__ = [
    "named_lock", "named_condition", "enable", "disable", "is_enabled",
    "boundary", "report", "reset", "ENABLED",
]

# Module-level gate, read inline by the proxies and by the framework's
# boundary hooks (`if _locktrace.ENABLED: ...`) so the disabled cost is
# one attribute load + truth test.
ENABLED = _getenv("MXNET_DEBUG_LOCKS", "0") in ("1", "true", "on")

_tls = threading.local()  # .held: list of _NamedLock in acquisition order

# detector state; guarded by the (untraced) bookkeeping lock below
_graph_lock = threading.Lock()
_edges = {}        # (holder_name, acquired_name) -> count
_inversions = []   # {"pair", "first_seen", "stack"} — order-graph cycles
_boundaries = []   # {"boundary", "held", "stack"} — locks held at a sync
_acquisitions = 0  # total traced acquires (detector coverage indicator)
_registry = {}     # name -> number of live locks carrying it
_MAX_FINDINGS = 100  # bound the finding lists; totals keep counting
_inversion_total = 0
_boundary_total = 0


def _held():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack():
    # skip the locktrace frames themselves; cap depth — findings are
    # for humans, not for unbounded memory growth
    return "".join(traceback.format_stack(limit=12)[:-2])


class _NamedLock:
    """``threading.Lock``/``RLock`` proxy carrying a registry name.

    Disabled fast path: one module-attribute truth test on acquire and
    a thread-local peek on release (needed so a disable() with locks
    held cannot strand bookkeeping)."""

    __slots__ = ("name", "_lock")

    def __init__(self, name, reentrant=False):
        self.name = name
        self._lock = threading.RLock() if reentrant \
            else threading.Lock()

    # -- instrumentation core ------------------------------------------

    def _record_acquire(self):
        global _acquisitions, _inversion_total
        held = _held()
        with _graph_lock:
            _acquisitions += 1
            # one edge from EVERY held lock, not just the innermost —
            # a thread holding A and B while acquiring C can deadlock
            # against a thread doing C then A, so A->C must be in the
            # graph even though B was acquired in between
            for holder in {l.name for l in held}:
                if holder == self.name:
                    continue
                edge = (holder, self.name)
                inverse = (self.name, holder)
                fresh = edge not in _edges
                _edges[edge] = _edges.get(edge, 0) + 1
                if fresh and inverse in _edges:
                    _inversion_total += 1
                    if len(_inversions) < _MAX_FINDINGS:
                        _inversions.append({
                            "pair": [holder, self.name],
                            "held": [l.name for l in held],
                            "stack": _stack(),
                        })
        held.append(self)

    def _record_release(self):
        held = getattr(_tls, "held", None)
        if held:
            # usually LIFO, but Condition.wait releases out of order
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break

    # -- lock protocol --------------------------------------------------

    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got and ENABLED:
            self._record_acquire()
        return got

    def release(self):
        self._record_release()
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        # RLock grew .locked() only in 3.14; fall back to the probe
        f = getattr(self._lock, "locked", None)
        if f is not None:
            return f()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _is_owned(self):
        # Condition support: "owned" == this thread recorded the
        # acquire. A lock taken BEFORE a runtime enable() has no
        # record, so never answer a hard False from bookkeeping alone —
        # fall back to the acquire-probe heuristic CPython's Condition
        # uses for plain Locks ("locked at all" == owned).
        held = getattr(_tls, "held", None)
        if held is not None and self in held:
            return True
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __repr__(self):
        return "<_NamedLock %s>" % self.name


def named_lock(name, reentrant=False):
    """Allocate a framework lock under ``name``. All framework locks
    must come from here (mxlint MX003 points offenders at this factory);
    the name defines its node in the acquisition-order graph.
    ``reentrant=True`` backs it with an RLock — for critical sections
    that may legitimately re-enter on the same thread (plugin loads
    loading dependency plugins)."""
    with _graph_lock:
        _registry[name] = _registry.get(name, 0) + 1
    return _NamedLock(name, reentrant=reentrant)


def named_condition(name, lock=None):
    """``threading.Condition`` over a named (traced) lock."""
    return threading.Condition(lock if lock is not None
                               else named_lock(name))


def boundary(name):
    """Called at jit-compile / device-sync boundaries. Records every
    traced lock the calling thread holds — blocking device work while
    holding a framework lock is the race/starvation pattern this
    detector exists for. Callers guard with ``if locktrace.ENABLED:``
    so the disabled cost stays off the hot path."""
    global _boundary_total
    if not ENABLED:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    with _graph_lock:
        _boundary_total += 1
        if len(_boundaries) < _MAX_FINDINGS:
            _boundaries.append({
                "boundary": name,
                "held": [l.name for l in held],
                "stack": _stack(),
            })


def enable():
    """Turn the detector on at runtime (the env var sets the process
    default). Returns the previous state."""
    global ENABLED
    prev = ENABLED
    ENABLED = True
    return prev


def disable():
    global ENABLED
    prev = ENABLED
    ENABLED = False
    return prev


def is_enabled():
    return ENABLED


def reset():
    """Clear recorded findings (test isolation)."""
    global _acquisitions, _inversion_total, _boundary_total
    with _graph_lock:
        _edges.clear()
        _inversions.clear()
        _boundaries.clear()
        _acquisitions = 0
        _inversion_total = 0
        _boundary_total = 0


def report():
    """JSON-safe snapshot of everything the detector recorded. Embedded
    in ``profiler.metrics()['locks']`` while tracing is enabled."""
    with _graph_lock:
        return {
            "enabled": ENABLED,
            "locks": sorted(_registry),
            "acquisitions": _acquisitions,
            "order_edges": sorted(
                "%s->%s" % e for e in _edges),
            "inversions": [dict(i) for i in _inversions],
            "inversion_total": _inversion_total,
            "boundary_violations": [dict(b) for b in _boundaries],
            "boundary_violation_total": _boundary_total,
        }
